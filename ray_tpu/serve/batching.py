"""@serve.batch: transparent request batching inside a replica.

Equivalent of the reference's ``python/ray/serve/batching.py:80``
(``@serve.batch``): individual calls to the decorated method queue up;
the underlying function runs ONCE per batch with a list of inputs and
must return a list of outputs of the same length. A batch fires when
``max_batch_size`` items are waiting or ``batch_wait_timeout_s`` has
elapsed since the first item arrived.

Replica methods execute on worker threads here (not an asyncio loop), so
the batcher is thread-based: callers block on a per-item event while a
lazily-started batcher thread drains the queue. Exceptions from the
batch function propagate to every caller in that batch.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable


class _Item:
    __slots__ = ("args", "kwargs", "result", "error", "done", "trace_ctx")

    def __init__(self, args, kwargs):
        self.args = args
        self.kwargs = kwargs
        self.result = None
        self.error: BaseException | None = None
        self.done = threading.Event()
        # Captured at submit: the batcher thread has no caller context, so
        # the batch span parents onto the first traced item of the batch.
        from ..observability import tracing

        self.trace_ctx = tracing.current()


class _Batcher:
    def __init__(self, fn: Callable, instance: Any, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._instance = instance
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._queue: list[_Item] = []
        self._cond = threading.Condition()
        self._thread: threading.Thread | None = None
        self.num_batches = 0  # observability / tests

    def submit(self, args, kwargs) -> Any:
        item = _Item(args, kwargs)
        with self._cond:
            self._queue.append(item)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop, daemon=True)
                self._thread.start()
            self._cond.notify_all()
        item.done.wait()
        if item.error is not None:
            raise item.error
        return item.result

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue:
                    # Idle exit after a grace period: replicas churn, and a
                    # parked thread per batched method would accumulate.
                    if not self._cond.wait(timeout=10.0):
                        if not self._queue:
                            self._thread = None
                            return
                deadline = time.monotonic() + self.batch_wait_timeout_s
                while (len(self._queue) < self.max_batch_size
                       and time.monotonic() < deadline):
                    self._cond.wait(timeout=max(0.0, deadline - time.monotonic()))
                batch, self._queue = (self._queue[:self.max_batch_size],
                                      self._queue[self.max_batch_size:])
            self._run_batch(batch)

    def _run_batch(self, batch: list[_Item]) -> None:
        from ..observability import tracing

        self.num_batches += 1
        inputs = [it.args[0] if it.args else None for it in batch]
        ctx = next((it.trace_ctx for it in batch if it.trace_ctx is not None), None)
        t0 = time.time()
        prev = tracing.set_current(ctx) if ctx is not None else None
        try:
            if self._instance is not None:
                outputs = self._fn(self._instance, inputs)
            else:
                outputs = self._fn(inputs)
            import inspect

            if inspect.iscoroutine(outputs):
                import asyncio

                outputs = asyncio.run(outputs)
            if len(outputs) != len(batch):
                raise ValueError(
                    f"@serve.batch function returned {len(outputs)} results "
                    f"for a batch of {len(batch)}")
            for it, out in zip(batch, outputs):
                it.result = out
                it.done.set()
        except BaseException as e:
            for it in batch:
                it.error = e
                it.done.set()
        finally:
            if ctx is not None:
                tracing.record_span(tracing.make_span(
                    f"serve.batch {getattr(self._fn, '__name__', 'fn')}",
                    "serve", t0, time.time(), ctx.trace_id, ctx.span_id,
                    attrs={"batch_size": len(batch)}))
                tracing.set_current(prev)


# Deployment classes are cloudpickled to replicas, so decorator closures
# must stay lock-free: per-instance batchers live ON the instance (created
# under this importable module-level lock, which pickles by reference),
# and free-function batchers in a module-level registry.
_CREATE_LOCK = threading.Lock()
_FUNC_BATCHERS: dict[str, _Batcher] = {}


def _batcher_for(fn: Callable, instance: Any, max_batch_size: int,
                 batch_wait_timeout_s: float) -> _Batcher:
    if instance is not None:
        attr = f"_serve_batcher_{fn.__name__}"
        b = getattr(instance, attr, None)
        if b is None:
            with _CREATE_LOCK:
                b = getattr(instance, attr, None)
                if b is None:
                    b = _Batcher(fn, instance, max_batch_size, batch_wait_timeout_s)
                    setattr(instance, attr, b)
        return b
    key = f"{fn.__module__}.{fn.__qualname__}"
    with _CREATE_LOCK:
        b = _FUNC_BATCHERS.get(key)
        if b is None:
            b = _FUNC_BATCHERS[key] = _Batcher(
                fn, None, max_batch_size, batch_wait_timeout_s)
        return b


def batch(_func: Callable | None = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped function must accept a LIST of requests and
    return a LIST of results (reference ``serve.batch``). Works on both
    replica methods and free functions; each bound instance gets its own
    batcher (one engine per replica)."""

    def wrap(fn: Callable):
        import inspect

        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"

        @functools.wraps(fn)
        def method_wrapper(self, single, **kwargs):
            return _batcher_for(fn, self, max_batch_size,
                                batch_wait_timeout_s).submit((single,), kwargs)

        @functools.wraps(fn)
        def func_wrapper(single, **kwargs):
            return _batcher_for(fn, None, max_batch_size,
                                batch_wait_timeout_s).submit((single,), kwargs)

        wrapper = method_wrapper if is_method else func_wrapper
        wrapper.__wrapped__ = fn
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap
