"""Model multiplexing: many models behind one deployment.

Equivalent of the reference's ``python/ray/serve/multiplex.py:22``
(``@serve.multiplexed`` + ``get_multiplexed_model_id``): a replica hosts
up to ``max_num_models_per_replica`` models, loading on demand and
evicting least-recently-used. The target model id travels with the
request — the ``serve_multiplexed_model_id`` HTTP header, or
``handle.options(multiplexed_model_id=...)`` — and the router prefers
replicas that have served that model recently (cache affinity).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Callable

_request_context = threading.local()

MULTIPLEXED_MODEL_ID_HEADER = "serve_multiplexed_model_id"
# Tenancy spelling of the same routing key (multi-tenant LoRA
# multiplexing): both headers — and an OpenAI-style JSON body ``model``
# field — resolve to ONE model id at the proxy, so a client using either
# lands on the same resident replica.
X_RAYTPU_MODEL_HEADER = "x-raytpu-model"
MULTIPLEXED_KWARG = "_serve_multiplexed_model_id"


def resolve_model_id(headers: dict, body: "dict | None" = None) -> str:
    """Unify the multiplex header spellings into one routing key:
    ``serve_multiplexed_model_id`` wins (backward compat), then
    ``x-raytpu-model``, then the request body's ``model`` field. Header
    lookup is case-insensitive (HTTP semantics)."""
    lowered = {str(k).lower(): v for k, v in (headers or {}).items()}
    mid = lowered.get(MULTIPLEXED_MODEL_ID_HEADER) \
        or lowered.get(X_RAYTPU_MODEL_HEADER)
    if not mid and isinstance(body, dict):
        mid = body.get("model")
    return str(mid) if mid else ""


def set_multiplexed_model_id(model_id: str) -> None:
    """Install the target model id for the current request thread
    (called by the replica before invoking the user callable)."""
    _request_context.model_id = model_id


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request targets (reference
    ``serve.get_multiplexed_model_id``)."""
    return getattr(_request_context, "model_id", "")


class _ModelCache:
    """Per-instance LRU of loaded models with single-flight loading."""

    def __init__(self, loader: Callable, instance: Any, max_models: int):
        self._loader = loader
        self._instance = instance
        self._max = max_models
        self._models: OrderedDict[str, Any] = OrderedDict()
        self._loading: dict[str, threading.Event] = {}
        self._lock = threading.Lock()

    def get(self, model_id: str) -> Any:
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    self._loading[model_id] = ev = threading.Event()
                    break
            ev.wait()  # another thread is loading the same model
        try:
            model = self._loader(self._instance, model_id) \
                if self._instance is not None else self._loader(model_id)
            import inspect

            if inspect.iscoroutine(model):
                import asyncio

                model = asyncio.run(model)
            evicted = None
            with self._lock:
                self._models[model_id] = model
                if len(self._models) > self._max:
                    _, evicted = self._models.popitem(last=False)
            if evicted is not None:
                # Reference calls __del__/cleanup hooks on evicted models.
                unload = getattr(evicted, "unload", None)
                if callable(unload):
                    try:
                        unload()
                    except Exception:
                        pass
            return model
        finally:
            with self._lock:
                self._loading.pop(model_id, None)
            ev.set()

    def loaded_ids(self) -> list[str]:
        with self._lock:
            return list(self._models)


# Deployment classes are cloudpickled to replicas: keep decorator closures
# lock-free (see batching.py) — caches live on instances / in this module.
_CREATE_LOCK = threading.Lock()
_FUNC_CACHES: dict[str, _ModelCache] = {}


def _cache_for(fn: Callable, instance: Any, max_models: int) -> _ModelCache:
    if instance is not None:
        attr = f"_serve_model_cache_{fn.__name__}"
        c = getattr(instance, attr, None)
        if c is None:
            with _CREATE_LOCK:
                c = getattr(instance, attr, None)
                if c is None:
                    c = _ModelCache(fn, instance, max_models)
                    setattr(instance, attr, c)
        return c
    key = f"{fn.__module__}.{fn.__qualname__}"
    with _CREATE_LOCK:
        c = _FUNC_CACHES.get(key)
        if c is None:
            c = _FUNC_CACHES[key] = _ModelCache(fn, None, max_models)
        return c


def multiplexed(_func: Callable | None = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a model-loader method ``def get_model(self, model_id)``
    (reference ``@serve.multiplexed``). Calls return the loaded model,
    loading on first use and LRU-evicting beyond the cap."""

    def wrap(fn: Callable):
        import inspect

        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"

        @functools.wraps(fn)
        def method_wrapper(self, model_id: str | None = None):
            mid = model_id if model_id is not None else get_multiplexed_model_id()
            return _cache_for(fn, self, max_num_models_per_replica).get(mid)

        @functools.wraps(fn)
        def func_wrapper(model_id: str | None = None):
            mid = model_id if model_id is not None else get_multiplexed_model_id()
            return _cache_for(fn, None, max_num_models_per_replica).get(mid)

        wrapper = method_wrapper if is_method else func_wrapper
        wrapper.__wrapped__ = fn
        return wrapper

    if _func is not None:
        return wrap(_func)
    return wrap
