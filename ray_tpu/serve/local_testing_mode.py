"""In-process Serve deployments for unit tests — no cluster boot.

Reference: ``python/ray/serve/_private/local_testing_mode.py:49``
(``make_local_deployment_handle``). Deployments are instantiated in THIS
process and driven through the real ``Replica`` request path
(``replica.py`` — method resolution, multiplex kwarg, reconfigure,
streaming), so a handler unit-tested here behaves identically on a real
replica actor; what's skipped is the cluster: controller, proxy, router,
and actor scheduling. A serve test that needs none of those drops from
tens of seconds (cluster boot) to milliseconds.

Use either directly::

    handle = make_local_deployment_handle(MyDeployment.bind(arg))
    assert handle.remote(1).result() == 2

or through the public API::

    handle = serve.run(app, _local_testing_mode=True)
"""

from __future__ import annotations

import concurrent.futures
from typing import Any

import cloudpickle

from .deployment import Application

# Shared pool: nested handle calls from inside a handler must not
# deadlock on the caller's own worker thread.
_POOL = concurrent.futures.ThreadPoolExecutor(max_workers=32,
                                              thread_name_prefix="serve-local")


class LocalDeploymentResponse:
    """Future-backed stand-in for ``DeploymentResponse``."""

    def __init__(self, fut: concurrent.futures.Future):
        self._fut = fut

    def result(self, timeout: float | None = 60.0):
        return self._fut.result(timeout)


class LocalStreamingResponse:
    """Iterates the handler's generator — ``DeploymentStreamingResponse``
    stand-in (items arrive as produced; here the handler runs lazily on
    the consumer's thread, which is fine for tests)."""

    def __init__(self, gen):
        self._gen = gen

    def __iter__(self):
        return iter(self._gen)


class LocalDeploymentHandle:
    """Mirrors the ``DeploymentHandle`` call surface against an
    in-process ``Replica``."""

    def __init__(self, replica, deployment_name: str, method_name: str = "",
                 multiplexed_model_id: str = ""):
        self._replica = replica
        self.deployment_name = deployment_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id

    def __getattr__(self, name: str) -> "LocalDeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return LocalDeploymentHandle(self._replica, self.deployment_name,
                                     name, self._multiplexed_model_id)

    def options(self, *, method_name: str | None = None,
                multiplexed_model_id: str = "") -> "LocalDeploymentHandle":
        return LocalDeploymentHandle(
            self._replica, self.deployment_name,
            method_name if method_name is not None else self._method_name,
            multiplexed_model_id or self._multiplexed_model_id)

    def _kwargs(self, kwargs: dict) -> dict:
        if self._multiplexed_model_id:
            from .multiplex import MULTIPLEXED_KWARG

            kwargs = dict(kwargs)
            kwargs[MULTIPLEXED_KWARG] = self._multiplexed_model_id
        return kwargs

    def remote(self, *args, **kwargs) -> LocalDeploymentResponse:
        fut = _POOL.submit(self._replica.handle_request, self._method_name,
                           args, self._kwargs(kwargs))
        return LocalDeploymentResponse(fut)

    def remote_streaming(self, *args, **kwargs) -> LocalStreamingResponse:
        return LocalStreamingResponse(self._replica.handle_request_streaming(
            self._method_name, args, self._kwargs(kwargs)))


def make_local_deployment_handle(app: Application,
                                 app_name: str = "local") -> LocalDeploymentHandle:
    """Instantiate the application graph in-process and return a handle
    to its ingress. Shared nodes (diamond graphs) are instantiated once;
    nested ``Application`` init args become local handles."""
    from .api import _deployment_config
    from .replica import ReplicaActor as Replica
    from .router import HANDLE_MARKER

    nodes = app.walk()
    configs = {n.deployment.name: _deployment_config(n, app_name) for n in nodes}
    replicas: dict[str, Replica] = {}

    def build(name: str) -> Replica:
        if name in replicas:
            return replicas[name]
        cfg = configs[name]

        def decode(a):
            if isinstance(a, dict) and a.get("t") == HANDLE_MARKER:
                dep = a["deployment"]
                return LocalDeploymentHandle(build(dep), dep)
            return a

        init_args = tuple(decode(a) for a in cfg["init_args"])
        init_kwargs = {k: decode(v) for k, v in cfg["init_kwargs"].items()}
        replicas[name] = Replica(cfg["serialized_callable"], init_args,
                                 init_kwargs, cfg.get("user_config"))
        return replicas[name]

    ingress = app.deployment.name
    return LocalDeploymentHandle(build(ingress), ingress)
