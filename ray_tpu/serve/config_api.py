"""Declarative Serve config: YAML/dict app specs + REST deployment.

Equivalent of the reference's ``python/ray/serve/schema.py``
(ServeDeploySchema) + ``serve run config.yaml`` + the dashboard's
``/api/serve/applications`` REST endpoints: applications are described
as data — import path, args, per-deployment overrides — and deployed
without touching Python.

Schema::

    applications:
      - name: my_app
        route_prefix: /my
        import_path: my_module:app_builder   # Application OR callable
        args: {preset: debug-128}            # kwargs for a builder
        deployments:                         # per-deployment overrides
          - name: LLMDeployment
            num_replicas: 2
            max_ongoing_requests: 16
"""

from __future__ import annotations

import importlib
from typing import Any

from .deployment import Application


def _resolve_import(import_path: str):
    module_name, _, attr = import_path.partition(":")
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split(".") if attr else []:
        target = getattr(target, part)
    return target


def build_app_from_spec(spec: dict) -> Application:
    """Build one application from a config entry (reference
    ``serve/_private/api.py`` build_app)."""
    target = _resolve_import(spec["import_path"])
    if isinstance(target, Application):
        if spec.get("args"):
            raise ValueError(
                f"{spec['import_path']} is a bound Application; `args` only "
                "apply to builder functions")
        app = target
    elif callable(target):
        app = target(**(spec.get("args") or {}))
    else:
        raise TypeError(f"{spec['import_path']} is not an Application or builder")
    if not isinstance(app, Application):
        raise TypeError(f"{spec['import_path']} did not produce an Application")
    # App-level runtime_env (reference schema: ships the import_path's
    # code to replicas via py_modules/working_dir/pip).
    app_renv = spec.get("runtime_env")
    # Per-deployment overrides (num_replicas etc).
    overrides = {d["name"]: d for d in (spec.get("deployments") or [])}
    for node in app.walk():
        if app_renv:
            opts = dict(node.deployment.ray_actor_options or {})
            opts.setdefault("runtime_env", app_renv)
            node.deployment.ray_actor_options = opts
        o = overrides.get(node.deployment.name)
        if not o:
            continue
        for key in ("num_replicas", "max_ongoing_requests", "user_config"):
            if key in o:
                setattr(node.deployment, key if key != "num_replicas" else "num_replicas",
                        o[key])
        if "autoscaling_config" in o:
            from .deployment import AutoscalingConfig

            node.deployment.autoscaling_config = AutoscalingConfig(**o["autoscaling_config"])
        if "ray_actor_options" in o:
            node.deployment.ray_actor_options = o["ray_actor_options"]
    return app


def deploy_config(config: dict | str, *, _blocking: bool = True) -> dict:
    """Deploy every application in a config dict, YAML string, or YAML
    file path (reference ``serve deploy`` / ServeDeploySchema)."""
    from . import api as serve_api

    config = _load(config)
    deployed = {}
    for spec in config.get("applications", []):
        app = build_app_from_spec(spec)
        name = spec.get("name", "default")
        serve_api.run(app, name=name,
                      route_prefix=spec.get("route_prefix", f"/{name}"),
                      _blocking=_blocking)
        deployed[name] = spec.get("route_prefix", f"/{name}")
    return deployed


def _load(config: dict | str) -> dict:
    if isinstance(config, dict):
        return config
    import os

    import yaml

    if os.path.exists(config):
        with open(config) as f:
            return yaml.safe_load(f)
    return yaml.safe_load(config)


def serve_status() -> dict:
    """Application/deployment status for the REST surface (reference
    ``serve status`` / GET /api/serve/applications/)."""
    from ..core import api as ray
    from .router import CONTROLLER_NAME

    try:
        controller = ray.get_actor(CONTROLLER_NAME)
    except ValueError:
        return {"applications": {}}
    deps = ray.get(controller.list_deployments.remote(), timeout=30)
    out: dict[str, Any] = {}
    for app, dep_map in deps.items():
        statuses = ray.get(controller.get_app_status.remote(app), timeout=30)
        live = {k: v for k, v in statuses.items() if not v.get("deleted")}
        out[app] = {
            "status": "RUNNING" if live and all(v["healthy"] for v in live.values())
            else ("DELETED" if not live else "DEPLOYING"),
            "deployments": {
                k: {"healthy": v["healthy"], "replicas": v.get("replicas", 0)}
                for k, v in live.items()
            },
        }
    return {"applications": out}
