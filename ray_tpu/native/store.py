"""ctypes binding for the native shared-memory object store.

The C++ library (``src/shm_store.cc``) is the plasma equivalent
(reference ``src/ray/object_manager/plasma/store.h:55``); this module
auto-builds it with g++ on first import (no pip/pybind11 dependency) and
exposes a thread-safe :class:`ShmStore` owner handle plus a lightweight
:class:`ShmClient` that other processes use for zero-copy reads/writes via
``mmap`` of the same /dev/shm file.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "shm_store.cc")
_LIB = os.path.join(_DIR, "libshm_store.so")

_lib_handle = None
_lib_lock = threading.Lock()


def _build_if_needed() -> str:
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
        )
    return _LIB


def _load():
    global _lib_handle
    with _lib_lock:
        if _lib_handle is None:
            lib = ctypes.CDLL(_build_if_needed())
            u64, u32, u8p = ctypes.c_uint64, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8)
            vp, i32 = ctypes.c_void_p, ctypes.c_int
            lib.store_create.restype = vp
            lib.store_create.argtypes = [ctypes.c_char_p, u64]
            lib.store_destroy.argtypes = [vp]
            lib.store_create_object.restype = i32
            lib.store_create_object.argtypes = [vp, ctypes.c_char_p, u32, u64, u64, ctypes.POINTER(u64)]
            lib.store_seal.restype = i32
            lib.store_seal.argtypes = [vp, ctypes.c_char_p, u32]
            lib.store_get.restype = i32
            lib.store_get.argtypes = [vp, ctypes.c_char_p, u32, ctypes.POINTER(u64), ctypes.POINTER(u64), ctypes.POINTER(u64)]
            for name in ("store_add_ref", "store_release", "store_contains",
                         "store_pin", "store_unpin"):
                fn = getattr(lib, name)
                fn.restype = i32
                fn.argtypes = [vp, ctypes.c_char_p, u32]
            lib.store_ref_count.restype = ctypes.c_int64
            lib.store_ref_count.argtypes = [vp, ctypes.c_char_p, u32]
            lib.store_delete.restype = i32
            lib.store_delete.argtypes = [vp, ctypes.c_char_p, u32, i32]
            lib.store_evict.restype = u64
            lib.store_evict.argtypes = [vp, u64]
            for name in ("store_used", "store_capacity", "store_num_objects"):
                fn = getattr(lib, name)
                fn.restype = u64
                fn.argtypes = [vp]
            _lib_handle = lib
        return _lib_handle


class ShmStoreError(Exception):
    pass


class ObjectExistsError(ShmStoreError):
    pass


class StoreFullError(ShmStoreError):
    pass


class ShmStore:
    """Owner-side handle: allocation, sealing, eviction, refcounts.

    Lives inside the raylet process (single writer); all methods are
    guarded by a lock so RPC handlers may call from multiple tasks.
    """

    def __init__(self, path: str, capacity: int):
        self._lib = _load()
        self.path = path
        self.capacity = capacity
        self._handle = self._lib.store_create(path.encode(), capacity)
        if not self._handle:
            raise ShmStoreError(f"Failed to create store at {path}")
        self._lock = threading.Lock()
        self._mm = ShmClient(path, capacity)

    def create(self, object_id: bytes, data_size: int, meta_size: int = 0) -> int:
        """Allocate space; returns byte offset into the arena."""
        from ..core.rpc import get_chaos

        if get_chaos().maybe_fail_store_create():
            # Chaos injection point (store_full FaultPlan rule): surface
            # as the real allocation failure so callers exercise their
            # spill / fallback-allocation paths.
            raise StoreFullError(
                f"chaos-injected store-full creating {object_id.hex()}")
        offset = ctypes.c_uint64()
        with self._lock:
            rc = self._lib.store_create_object(
                self._handle, object_id, len(object_id), data_size, meta_size, ctypes.byref(offset)
            )
        if rc == -1:
            raise ObjectExistsError(object_id.hex())
        if rc == -2:
            raise StoreFullError(
                f"Object of {data_size + meta_size} bytes doesn't fit "
                f"(capacity {self.capacity}, used {self.used()})"
            )
        return offset.value

    def seal(self, object_id: bytes) -> None:
        with self._lock:
            rc = self._lib.store_seal(self._handle, object_id, len(object_id))
        if rc != 0:
            raise ShmStoreError(f"seal({object_id.hex()}) rc={rc}")

    def get_info(self, object_id: bytes) -> tuple[int, int, int] | None:
        """Return (offset, data_size, meta_size) for a sealed object, else None."""
        off, dsz, msz = ctypes.c_uint64(), ctypes.c_uint64(), ctypes.c_uint64()
        with self._lock:
            rc = self._lib.store_get(
                self._handle, object_id, len(object_id),
                ctypes.byref(off), ctypes.byref(dsz), ctypes.byref(msz),
            )
        if rc != 0:
            return None
        return off.value, dsz.value, msz.value

    def add_ref(self, object_id: bytes) -> None:
        with self._lock:
            self._lib.store_add_ref(self._handle, object_id, len(object_id))

    def release(self, object_id: bytes) -> None:
        with self._lock:
            self._lib.store_release(self._handle, object_id, len(object_id))

    def delete(self, object_id: bytes, force: bool = False) -> bool:
        with self._lock:
            return self._lib.store_delete(self._handle, object_id, len(object_id), int(force)) == 0

    def contains(self, object_id: bytes) -> int:
        """0 = absent, 1 = created/unsealed, 2 = sealed."""
        with self._lock:
            return self._lib.store_contains(self._handle, object_id, len(object_id))

    def pin(self, object_id: bytes) -> None:
        """Exclude a primary copy from LRU eviction (reference
        ``local_object_manager.h:110`` pinned-object semantics)."""
        with self._lock:
            self._lib.store_pin(self._handle, object_id, len(object_id))

    def unpin(self, object_id: bytes) -> None:
        with self._lock:
            self._lib.store_unpin(self._handle, object_id, len(object_id))

    def ref_count(self, object_id: bytes) -> int:
        """-1 if absent."""
        with self._lock:
            return self._lib.store_ref_count(self._handle, object_id, len(object_id))

    def evict(self, nbytes: int) -> int:
        with self._lock:
            return self._lib.store_evict(self._handle, nbytes)

    def used(self) -> int:
        with self._lock:
            return self._lib.store_used(self._handle)

    def num_objects(self) -> int:
        with self._lock:
            return self._lib.store_num_objects(self._handle)

    # -- direct data access (owner process shares the same mmap) ------------
    def write(self, offset: int, data: bytes | memoryview) -> None:
        self._mm.write(offset, data)

    def read(self, offset: int, size: int) -> memoryview:
        return self._mm.read(offset, size)

    def put_sealed(self, object_id: bytes, data: bytes | memoryview, meta: bytes = b"") -> None:
        """Convenience: create + write data+meta + seal, creator ref released."""
        mv = memoryview(data)
        offset = self.create(object_id, mv.nbytes, len(meta))
        self._mm.write(offset, mv)
        if meta:
            self._mm.write(offset + mv.nbytes, meta)
        self.seal(object_id)
        self.release(object_id)

    def close(self) -> None:
        with self._lock:
            if self._handle:
                self._mm.close()
                self._lib.store_destroy(self._handle)
                self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmClient:
    """Zero-copy reader/writer used by worker processes: mmaps the arena file."""

    def __init__(self, path: str, capacity: int):
        self.path = path
        self._fd = os.open(path, os.O_RDWR)
        self._mm = mmap.mmap(self._fd, capacity)
        self._view = memoryview(self._mm)

    def read(self, offset: int, size: int) -> memoryview:
        return self._view[offset : offset + size]

    def write(self, offset: int, data: bytes | memoryview) -> None:
        mv = memoryview(data)
        self._view[offset : offset + mv.nbytes] = mv

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
            os.close(self._fd)
        except Exception:
            pass
