// Shared-memory object store: arena allocator + object table + LRU eviction.
//
// TPU-native equivalent of the reference's plasma store
// (src/ray/object_manager/plasma/: store.h:55, object_lifecycle_manager.h:106,
// eviction_policy.h:160, plasma_allocator.h). Design difference from plasma:
// instead of a standalone store process that passes fds over a unix socket
// (fling.cc), the store is a library embedded in the per-node raylet process.
// The arena is a file in /dev/shm; clients simply mmap the same path read-only
// and receive (offset, size) ranges over RPC — same zero-copy property,
// drastically less machinery.
//
// Concurrency: the embedding process serializes calls (Python side holds a
// lock); no internal locking needed beyond what the single writer provides.
//
// Build: g++ -O2 -shared -fPIC -o libshm_store.so shm_store.cc

#include <sys/mman.h>
#include <sys/stat.h>
#include <fcntl.h>
#include <unistd.h>
#include <cstdint>
#include <cstring>
#include <list>
#include <map>
#include <set>
#include <string>
#include <unordered_map>

namespace {

constexpr uint64_t kAlignment = 64;

inline uint64_t AlignUp(uint64_t n) { return (n + kAlignment - 1) & ~(kAlignment - 1); }

enum class ObjectState : uint8_t { kCreated = 0, kSealed = 1 };

struct Entry {
  uint64_t offset = 0;
  uint64_t data_size = 0;
  uint64_t meta_size = 0;
  uint64_t alloc_size = 0;
  int64_t ref_count = 0;
  ObjectState state = ObjectState::kCreated;
  // Pinned objects (primary copies, reference
  // local_object_manager.h:110 PinObjectsAndWaitForFree) are never
  // LRU-evicted; the embedding raylet must spill them to disk first.
  bool pinned = false;
  // Position in the LRU list when evictable (sealed && ref_count == 0 &&
  // !pinned).
  bool in_lru = false;
  std::list<std::string>::iterator lru_it;
};

// Best-fit free-list allocator with coalescing over [0, capacity).
// Plays the role of plasma's dlmalloc arena (plasma_allocator.h, dlmalloc.cc).
class Arena {
 public:
  explicit Arena(uint64_t capacity) : capacity_(capacity) {
    free_by_offset_[0] = capacity;
    InsertBySize(0, capacity);
  }

  bool Allocate(uint64_t size, uint64_t* offset_out) {
    size = AlignUp(size == 0 ? kAlignment : size);
    // Best fit: smallest free block >= size.
    auto it = free_by_size_.lower_bound({size, 0});
    if (it == free_by_size_.end()) return false;
    uint64_t block_size = it->first;
    uint64_t offset = it->second;
    free_by_size_.erase(it);
    free_by_offset_.erase(offset);
    if (block_size > size) {
      free_by_offset_[offset + size] = block_size - size;
      InsertBySize(offset + size, block_size - size);
    }
    used_ += size;
    *offset_out = offset;
    return true;
  }

  void Free(uint64_t offset, uint64_t size) {
    size = AlignUp(size == 0 ? kAlignment : size);
    used_ -= size;
    // Coalesce with successor.
    auto next = free_by_offset_.lower_bound(offset);
    if (next != free_by_offset_.end() && next->first == offset + size) {
      size += next->second;
      EraseBySize(next->first, next->second);
      free_by_offset_.erase(next);
    }
    // Coalesce with predecessor.
    auto prev = free_by_offset_.lower_bound(offset);
    if (prev != free_by_offset_.begin()) {
      --prev;
      if (prev->first + prev->second == offset) {
        offset = prev->first;
        size += prev->second;
        EraseBySize(prev->first, prev->second);
        free_by_offset_.erase(prev);
      }
    }
    free_by_offset_[offset] = size;
    InsertBySize(offset, size);
  }

  uint64_t used() const { return used_; }
  uint64_t capacity() const { return capacity_; }

 private:
  void InsertBySize(uint64_t offset, uint64_t size) {
    free_by_size_.insert({size, offset});
  }
  void EraseBySize(uint64_t offset, uint64_t size) {
    free_by_size_.erase({size, offset});
  }

  uint64_t capacity_;
  uint64_t used_ = 0;
  std::map<uint64_t, uint64_t> free_by_offset_;          // offset -> size
  std::set<std::pair<uint64_t, uint64_t>> free_by_size_;  // (size, offset)
};

class Store {
 public:
  Store(void* base, uint64_t capacity, int fd, bool owns_file, std::string path)
      : base_(static_cast<uint8_t*>(base)),
        arena_(capacity),
        fd_(fd),
        owns_file_(owns_file),
        path_(std::move(path)) {}

  ~Store() {
    munmap(base_, arena_.capacity());
    close(fd_);
    if (owns_file_) unlink(path_.c_str());
  }

  // rc: 0 ok, -1 already exists, -2 out of memory.
  int CreateObject(const std::string& id, uint64_t data_size, uint64_t meta_size,
                   uint64_t* offset_out) {
    if (table_.count(id)) return -1;
    uint64_t total = data_size + meta_size;
    uint64_t offset;
    if (!arena_.Allocate(total, &offset)) {
      // LRU-evict sealed unreferenced objects then retry
      // (eviction_policy.h:160 LRUCache::ChooseObjectsToEvict).
      EvictUntil(AlignUp(total));
      if (!arena_.Allocate(total, &offset)) return -2;
    }
    Entry e;
    e.offset = offset;
    e.data_size = data_size;
    e.meta_size = meta_size;
    e.alloc_size = total;
    e.state = ObjectState::kCreated;
    e.ref_count = 1;  // creator holds a ref until seal+release
    table_[id] = e;
    *offset_out = offset;
    return 0;
  }

  int Seal(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    if (it->second.state == ObjectState::kSealed) return -3;
    it->second.state = ObjectState::kSealed;
    num_sealed_++;
    return 0;
  }

  // rc: 0 ok, -1 missing, -2 not yet sealed.
  int Get(const std::string& id, uint64_t* offset, uint64_t* data_size,
          uint64_t* meta_size) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    if (it->second.state != ObjectState::kSealed) return -2;
    Touch(id, it->second);
    *offset = it->second.offset;
    *data_size = it->second.data_size;
    *meta_size = it->second.meta_size;
    return 0;
  }

  int AddRef(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    it->second.ref_count++;
    RemoveFromLru(id, it->second);
    return 0;
  }

  int Release(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    if (--it->second.ref_count <= 0) {
      it->second.ref_count = 0;
      if (it->second.state == ObjectState::kSealed) AddToLru(id, it->second);
    }
    return 0;
  }

  // rc: 0 ok, -1 missing, -2 still referenced.
  int Delete(const std::string& id, bool force) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    if (it->second.ref_count > 0 && !force) return -2;
    RemoveFromLru(id, it->second);
    if (it->second.state == ObjectState::kSealed) num_sealed_--;
    arena_.Free(it->second.offset, it->second.alloc_size);
    table_.erase(it);
    return 0;
  }

  int Contains(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return 0;
    return it->second.state == ObjectState::kSealed ? 2 : 1;
  }

  int Pin(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    it->second.pinned = true;
    RemoveFromLru(id, it->second);
    return 0;
  }

  int Unpin(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    it->second.pinned = false;
    if (it->second.state == ObjectState::kSealed && it->second.ref_count <= 0)
      AddToLru(id, it->second);
    return 0;
  }

  int64_t RefCount(const std::string& id) {
    auto it = table_.find(id);
    if (it == table_.end()) return -1;
    return it->second.ref_count;
  }

  uint64_t EvictUntil(uint64_t bytes_needed) {
    uint64_t freed = 0;
    while (freed < bytes_needed && !lru_.empty()) {
      std::string victim = lru_.front();  // front = least recently used
      auto it = table_.find(victim);
      if (it == table_.end()) {
        lru_.pop_front();
        continue;
      }
      freed += it->second.alloc_size;
      Delete(victim, /*force=*/false);
    }
    return freed;
  }

  uint64_t used() const { return arena_.used(); }
  uint64_t capacity() const { return arena_.capacity(); }
  uint64_t num_objects() const { return table_.size(); }
  uint64_t num_sealed() const { return num_sealed_; }
  uint8_t* base() const { return base_; }

 private:
  void Touch(const std::string& id, Entry& e) {
    if (e.in_lru) {
      lru_.erase(e.lru_it);
      e.lru_it = lru_.insert(lru_.end(), id);
    }
  }
  void AddToLru(const std::string& id, Entry& e) {
    if (e.pinned) return;
    if (!e.in_lru) {
      e.lru_it = lru_.insert(lru_.end(), id);
      e.in_lru = true;
    }
  }
  void RemoveFromLru(const std::string& id, Entry& e) {
    if (e.in_lru) {
      lru_.erase(e.lru_it);
      e.in_lru = false;
    }
  }

  uint8_t* base_;
  Arena arena_;
  int fd_;
  bool owns_file_;
  std::string path_;
  uint64_t num_sealed_ = 0;
  std::unordered_map<std::string, Entry> table_;
  std::list<std::string> lru_;
};

}  // namespace

extern "C" {

void* store_create(const char* path, uint64_t capacity) {
  int fd = open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  return new Store(base, capacity, fd, /*owns_file=*/true, path);
}

void store_destroy(void* s) { delete static_cast<Store*>(s); }

int store_create_object(void* s, const uint8_t* id, uint32_t id_len,
                        uint64_t data_size, uint64_t meta_size,
                        uint64_t* offset_out) {
  return static_cast<Store*>(s)->CreateObject(
      std::string(reinterpret_cast<const char*>(id), id_len), data_size,
      meta_size, offset_out);
}

int store_seal(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->Seal(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

int store_get(void* s, const uint8_t* id, uint32_t id_len, uint64_t* offset,
              uint64_t* data_size, uint64_t* meta_size) {
  return static_cast<Store*>(s)->Get(
      std::string(reinterpret_cast<const char*>(id), id_len), offset, data_size,
      meta_size);
}

int store_add_ref(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->AddRef(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

int store_release(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->Release(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

int store_delete(void* s, const uint8_t* id, uint32_t id_len, int force) {
  return static_cast<Store*>(s)->Delete(
      std::string(reinterpret_cast<const char*>(id), id_len), force != 0);
}

int store_contains(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->Contains(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

int store_pin(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->Pin(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

int store_unpin(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->Unpin(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

int64_t store_ref_count(void* s, const uint8_t* id, uint32_t id_len) {
  return static_cast<Store*>(s)->RefCount(
      std::string(reinterpret_cast<const char*>(id), id_len));
}

uint64_t store_evict(void* s, uint64_t nbytes) {
  return static_cast<Store*>(s)->EvictUntil(nbytes);
}

uint64_t store_used(void* s) { return static_cast<Store*>(s)->used(); }
uint64_t store_capacity(void* s) { return static_cast<Store*>(s)->capacity(); }
uint64_t store_num_objects(void* s) { return static_cast<Store*>(s)->num_objects(); }
uint8_t* store_base(void* s) { return static_cast<Store*>(s)->base(); }

}  // extern "C"
