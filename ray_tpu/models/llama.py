"""Llama-3-family decoder, TPU-first.

Design choices (vs. a torch port):
- Layers are **stacked and scanned** (`lax.scan`): one compiled block body
  regardless of depth; `jax.checkpoint` on the block body trades FLOPs for
  HBM (rematerialisation).
- Params are a plain pytree of jnp arrays; ``param_axes(config)`` returns a
  matching tree of logical-axis tuples consumed by
  ``ray_tpu.parallel.sharding`` — strategy changes never touch this file.
- Attention is the Pallas flash kernel (``ray_tpu.ops.flash_attention``)
  or ring attention over the ``sp`` mesh axis for long context.
- bf16 params/activations, f32 softmax/norm statistics and loss.

The reference has no model code of its own (models live in torch/vLLM
behind Train/Serve); this supplies the TPU-native equivalent.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops import (flash_attention, mha_reference, ring_attention, rms_norm,
                   apply_rope, ulysses_attention)
from ..parallel.sharding import shard_constraint


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    intermediate: int = 14_336
    head_dim: int = 128
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # attention implementation: "flash" | "ring" | "reference"
    attn_impl: str = "flash"
    remat: bool = True
    # "full": recompute the whole block in backward (min HBM);
    # "dots": save matmul outputs, recompute elementwise only (XLA
    # checkpoint_policies.dots_with_no_batch_dims_saveable) — trades HBM
    # for ~1 forward less recompute per step.
    remat_policy: str = "full"
    # MoE: when n_experts > 0 the MLP becomes a top-k routed expert layer
    # sharded over the ``ep`` mesh axis.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_aux_weight: float = 0.01
    moe_capacity_factor: float = 1.25
    # Pipeline parallelism: microbatches per step when the mesh has pp > 1.
    pipeline_microbatches: int = 4


PRESETS: dict[str, LlamaConfig] = {
    # llama-3-8b: the BASELINE.md north-star model
    "llama3-8b": LlamaConfig(),
    "llama3-1b": LlamaConfig(hidden=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                             intermediate=8192, head_dim=64),
    # Exact 8B layer dims (hidden 4096, 32 q-heads, head_dim 128) at 8
    # layers so params+optimizer fit one 16 GB chip: the honest per-layer
    # perf point for the 8B north star (MFU is computed from THIS config).
    "llama3-8b-proxy": LlamaConfig(n_layers=8),
    # tiny configs for tests / dryruns
    "debug": LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, intermediate=128, head_dim=16),
    "debug-128": LlamaConfig(vocab_size=512, hidden=128, n_layers=2, n_heads=4,
                             n_kv_heads=2, intermediate=256, head_dim=32),
    # MoE family (Mixtral-style top-2 routing)
    "llama-moe-debug": LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                                   n_kv_heads=2, intermediate=128, head_dim=16,
                                   moe_experts=4),
    "mixtral-8x7b-ish": LlamaConfig(hidden=4096, n_layers=32, n_heads=32,
                                    n_kv_heads=8, intermediate=14_336, head_dim=128,
                                    moe_experts=8),
}


def param_axes(config: LlamaConfig):
    """Tree of logical-axis tuples matching ``init_params`` output."""
    if config.moe_experts > 0:
        from .moe import moe_param_axes

        mlp_axes = moe_param_axes(prefix=("layers",))
    else:
        mlp_axes = {
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        }
    return {
        "embed": ("vocab_in", "embed"),
        "layers": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "mlp_norm": ("layers", "norm"),
            **mlp_axes,
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(config: LlamaConfig, key: jax.Array) -> dict:
    """Random init (truncated-normal fan-in scaling), stacked over layers."""
    c = config
    keys = jax.random.split(key, 9)
    L, H, E = c.n_layers, c.n_heads, c.hidden
    KH, D, M = c.n_kv_heads, c.head_dim, c.intermediate

    def norm_init(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    if c.moe_experts > 0:
        from .moe import init_moe_params

        mlp_params = init_moe_params(
            keys[5], hidden=E, expert_mlp=M, n_experts=c.moe_experts,
            dtype=c.dtype, n_layers=L,
        )
    else:
        mlp_params = {
            "w_gate": norm_init(keys[5], (L, E, M), E),
            "w_up": norm_init(keys[6], (L, E, M), E),
            "w_down": norm_init(keys[7], (L, M, E), M),
        }
    return {
        "embed": norm_init(keys[0], (c.vocab_size, E), E),
        "layers": {
            "attn_norm": jnp.ones((L, E), c.dtype),
            "wq": norm_init(keys[1], (L, E, H, D), E),
            "wk": norm_init(keys[2], (L, E, KH, D), E),
            "wv": norm_init(keys[3], (L, E, KH, D), E),
            "wo": norm_init(keys[4], (L, H, D, E), H * D),
            "mlp_norm": jnp.ones((L, E), c.dtype),
            **mlp_params,
        },
        "final_norm": jnp.ones((E,), c.dtype),
        "lm_head": norm_init(keys[8], (E, c.vocab_size), E),
    }


def _attention(q, k, v, config: LlamaConfig, mesh: Mesh | None):
    if (config.attn_impl in ("ring", "ulysses") and mesh is not None
            and mesh.shape["sp"] > 1):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        inner = ring_attention if config.attn_impl == "ring" else ulysses_attention
        spec = P(("dcn", "dp", "fsdp"), "tp", "sp", None)
        fn = shard_map(
            functools.partial(inner, axis="sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)
    if config.attn_impl == "reference":
        return mha_reference(q, k, v, causal=True)
    if config.attn_impl == "none":  # ablation: identity attention
        g = q.shape[1] // k.shape[1]
        return (q.reshape(q.shape[0], k.shape[1], g, *q.shape[2:]) * v[:, :, None]).reshape(q.shape)
    return flash_attention(q, k, v, causal=True)


def _block(x, layer, positions, config: LlamaConfig, mesh: Mesh | None,
           ep_axis: str | None = None):
    """One decoder block. x: [B, S, E] in config.dtype. ``ep_axis`` is set
    only when running per-device inside the pipeline shard_map (expert
    shard + psum combine)."""
    c = config

    def sc(t, axes):
        return shard_constraint(t, mesh, axes) if mesh is not None else t

    from jax.ad_checkpoint import checkpoint_name

    h = rms_norm(x, layer["attn_norm"], eps=c.norm_eps)
    q = jnp.einsum("bse,ehd->bhsd", h, layer["wq"])
    k = jnp.einsum("bse,ehd->bhsd", h, layer["wk"])
    v = jnp.einsum("bse,ehd->bhsd", h, layer["wv"])
    q = apply_rope(q, positions, theta=c.rope_theta)
    k = apply_rope(k, positions, theta=c.rope_theta)
    q = checkpoint_name(sc(q, ("batch", "heads", "seq", "head_dim")), "q")
    k = checkpoint_name(k, "k")
    v = checkpoint_name(v, "v")
    attn = checkpoint_name(_attention(q, k, v, c, mesh), "attn_out")
    attn_out = jnp.einsum("bhsd,hde->bse", attn, layer["wo"])
    x = x + sc(attn_out, ("batch", "seq", "embed_act"))

    h = rms_norm(x, layer["mlp_norm"], eps=c.norm_eps)
    aux = jnp.zeros((), jnp.float32)
    if c.moe_experts > 0:
        from .moe import moe_block

        down, aux = moe_block(h, layer, top_k=c.moe_top_k, ep_axis=ep_axis,
                              n_experts_global=c.moe_experts,
                              capacity_factor=c.moe_capacity_factor)
    else:
        gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"])
        up = jnp.einsum("bse,em->bsm", h, layer["w_up"])
        ff = jax.nn.silu(gate.astype(jnp.float32)).astype(c.dtype) * up
        ff = sc(ff, ("batch", "seq", "mlp"))
        down = jnp.einsum("bsm,me->bse", ff, layer["w_down"])
    return x + sc(down, ("batch", "seq", "embed_act")), aux


def _apply_remat(block, c: LlamaConfig):
    """Wrap a decoder block with the configured rematerialisation policy."""
    if not c.remat:
        return block
    if c.remat_policy == "dots":
        return jax.checkpoint(
            block, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if c.remat_policy == "attn":
        # save the attention path (q/k/v projections + kernel output,
        # ~2.7 GB at 8x2048 for 1b) so the backward's recompute skips
        # the attention forward entirely — the best HBM/FLOPs trade on
        # a 16 GB chip
        return jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.save_only_these_names(
                "q", "k", "v", "attn_out"
            ),
        )
    return jax.checkpoint(block)


def forward_hidden(params, tokens, config: LlamaConfig, *, mesh: Mesh | None = None,
                   return_aux: bool = False):
    """tokens [B, S] int32 -> final hidden states [B, S, E] in config.dtype.

    ``return_aux=True`` additionally returns the summed MoE load-balancing
    loss (always 0.0 for dense configs and on the pipelined path, which
    does not thread aux through the schedule yet)."""
    c = config
    b, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)
    x = params["embed"][tokens].astype(c.dtype)
    if mesh is not None:
        # Two-hop resharding. The gather's output inherits the table's
        # embed=fsdp sharding; jumping straight to batch=(dcn,dp,fsdp)
        # asks SPMD for a transition it can only do by replicating the
        # whole tensor (the dryrun's "Involuntary full rematerialization"
        # warning on dcn meshes). Hop 1 reshards batch/seq while KEEPING
        # embed on fsdp; hop 2 moves fsdp from embed to batch — each a
        # single-axis change XLA lowers to cheap collectives.
        if mesh.shape.get("fsdp", 1) > 1:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _P(("dcn", "dp"), "sp", "fsdp")))
        x = shard_constraint(x, mesh, ("batch", "seq", "embed_act"))

    if mesh is not None and "pp" in mesh.shape and mesh.shape["pp"] > 1:
        # Pipelined path: stages over the pp axis, microbatch schedule via
        # shard_map + ppermute (parallel/pipeline.py). Blocks run as pure
        # per-device compute; MoE experts shard over ep inside the
        # shard_map (psum combine).
        from jax.sharding import PartitionSpec as P

        from ..parallel.pipeline import pipeline_apply

        ep_axis = "ep" if c.moe_experts > 0 and mesh.shape.get("ep", 1) > 1 else None
        raw_block = functools.partial(
            _block, positions=positions, config=c, mesh=None, ep_axis=ep_axis
        )
        block = _apply_remat(lambda carry, layer: raw_block(carry, layer)[0], c)
        # per-param specs: layers dim over pp; EXPERT WEIGHT dims over ep.
        # The router stays replicated across ep — routing is global (every
        # device scores all experts, then computes only its local shard).
        expert_weights = ("w_gate", "w_up", "w_down")
        param_specs = {
            name: (P("pp", "ep") if (ep_axis and c.moe_experts > 0 and name in expert_weights)
                   else P("pp"))
            for name in param_axes(c)["layers"]
        }
        x = pipeline_apply(
            block, params["layers"], x,
            mesh=mesh, n_microbatches=c.pipeline_microbatches,
            param_specs=param_specs,
        )
        out = rms_norm(x, params["final_norm"], eps=c.norm_eps)
        return (out, jnp.zeros((), jnp.float32)) if return_aux else out

    block = _apply_remat(
        functools.partial(_block, positions=positions, config=c, mesh=mesh), c
    )

    def scan_body(carry, layer):
        new_x, aux = block(carry, layer)
        return new_x, aux

    x, aux_per_layer = lax.scan(scan_body, x, params["layers"])
    out = rms_norm(x, params["final_norm"], eps=c.norm_eps)
    if return_aux:
        return out, jnp.sum(aux_per_layer)
    return out


def forward(params, tokens, config: LlamaConfig, *, mesh: Mesh | None = None):
    """tokens [B, S] int32 -> logits [B, S, vocab] f32. For inference/tests;
    training uses ``loss_fn`` which never materializes full logits."""
    x = forward_hidden(params, tokens, config, mesh=mesh)
    logits = jnp.einsum("bse,ev->bsv", x, params["lm_head"])
    return logits.astype(jnp.float32)


def train_flops_per_token(config: LlamaConfig, seq: int) -> float:
    """Model FLOPs per trained token (6N active-param matmul + causal
    attention), the numerator of MFU. Embedding gather excluded (standard
    accounting); MoE counts the top_k ACTIVE experts plus the router."""
    c = config
    if c.moe_experts > 0:
        mlp = c.moe_top_k * 3 * c.hidden * c.intermediate + c.hidden * c.moe_experts
    else:
        mlp = 3 * c.hidden * c.intermediate
    n_params = c.n_layers * (
        c.hidden * c.head_dim * (c.n_heads * 2 + c.n_kv_heads * 2) + mlp
    ) + c.hidden * c.vocab_size
    attn = 6 * c.n_layers * c.n_heads * c.head_dim * seq  # causal fwd+bwd
    return 6.0 * n_params + attn


def loss_fn(
    params,
    batch,
    config: LlamaConfig,
    *,
    mesh: Mesh | None = None,
    chunk_tokens: int = 512,
):
    """Next-token cross entropy. batch: {"tokens": [B,S], "mask": [B,S]}.

    The lm_head matmul is fused into a rematerialized scan over token
    chunks so the [B,S,vocab] logits tensor never exists in HBM — at 128k
    vocab that tensor alone would OOM a v5e chip at batch 8 × 2048.
    """
    tokens = batch["tokens"]
    aux = jnp.zeros((), jnp.float32)
    if config.moe_experts > 0:
        hidden, aux = forward_hidden(params, tokens, config, mesh=mesh, return_aux=True)
    else:
        hidden = forward_hidden(params, tokens, config, mesh=mesh)
    targets = tokens[:, 1:]
    hidden = hidden[:, :-1]
    mask = batch.get("mask")
    mask = (jnp.ones_like(targets, jnp.float32) if mask is None
            else mask[:, 1:].astype(jnp.float32))

    b, s, e = hidden.shape
    n = b * s
    flat_h = hidden.reshape(n, e)
    flat_t = targets.reshape(n)
    flat_m = mask.reshape(n)
    chunk = min(chunk_tokens, n)
    if n % chunk:
        pad = chunk - n % chunk
        flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
        flat_t = jnp.pad(flat_t, (0, pad))
        flat_m = jnp.pad(flat_m, (0, pad))
        n += pad
    nc = n // chunk
    lm_head = params["lm_head"]

    @jax.checkpoint
    def chunk_loss(xs):
        h, t, m = xs
        logits = jnp.einsum(
            "ce,ev->cv", h, lm_head, preferred_element_type=jnp.float32
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, t[:, None], axis=-1)[:, 0] - lse
        return (ll * m).sum()

    def body(carry, xs):
        return carry + chunk_loss(xs), None

    total, _ = lax.scan(
        body,
        jnp.zeros((), jnp.float32),
        (flat_h.reshape(nc, chunk, e), flat_t.reshape(nc, chunk),
         flat_m.reshape(nc, chunk)),
    )
    ce = -total / jnp.maximum(flat_m.sum(), 1.0)
    return ce + config.moe_aux_weight * aux
