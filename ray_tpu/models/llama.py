"""Llama-3-family decoder, TPU-first.

Design choices (vs. a torch port):
- Layers are **stacked and scanned** (`lax.scan`): one compiled block body
  regardless of depth; `jax.checkpoint` on the block body trades FLOPs for
  HBM (rematerialisation).
- Params are a plain pytree of jnp arrays; ``param_axes(config)`` returns a
  matching tree of logical-axis tuples consumed by
  ``ray_tpu.parallel.sharding`` — strategy changes never touch this file.
- Attention is the Pallas flash kernel (``ray_tpu.ops.flash_attention``)
  or ring attention over the ``sp`` mesh axis for long context.
- bf16 params/activations, f32 softmax/norm statistics and loss.

The reference has no model code of its own (models live in torch/vLLM
behind Train/Serve); this supplies the TPU-native equivalent.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops import flash_attention, mha_reference, ring_attention, rms_norm, apply_rope
from ..parallel.sharding import shard_constraint


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    intermediate: int = 14_336
    head_dim: int = 128
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # attention implementation: "flash" | "ring" | "reference"
    attn_impl: str = "flash"
    remat: bool = True
    # "full": recompute the whole block in backward (min HBM);
    # "dots": save matmul outputs, recompute elementwise only (XLA
    # checkpoint_policies.dots_with_no_batch_dims_saveable) — trades HBM
    # for ~1 forward less recompute per step.
    remat_policy: str = "full"


PRESETS: dict[str, LlamaConfig] = {
    # llama-3-8b: the BASELINE.md north-star model
    "llama3-8b": LlamaConfig(),
    "llama3-1b": LlamaConfig(hidden=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                             intermediate=8192, head_dim=64),
    # tiny configs for tests / dryruns
    "debug": LlamaConfig(vocab_size=256, hidden=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, intermediate=128, head_dim=16),
    "debug-128": LlamaConfig(vocab_size=512, hidden=128, n_layers=2, n_heads=4,
                             n_kv_heads=2, intermediate=256, head_dim=32),
}


def param_axes(config: LlamaConfig):
    """Tree of logical-axis tuples matching ``init_params`` output."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "mlp_norm": ("layers", "norm"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(config: LlamaConfig, key: jax.Array) -> dict:
    """Random init (truncated-normal fan-in scaling), stacked over layers."""
    c = config
    keys = jax.random.split(key, 9)
    L, H, E = c.n_layers, c.n_heads, c.hidden
    KH, D, M = c.n_kv_heads, c.head_dim, c.intermediate

    def norm_init(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    return {
        "embed": norm_init(keys[0], (c.vocab_size, E), E),
        "layers": {
            "attn_norm": jnp.ones((L, E), c.dtype),
            "wq": norm_init(keys[1], (L, E, H, D), E),
            "wk": norm_init(keys[2], (L, E, KH, D), E),
            "wv": norm_init(keys[3], (L, E, KH, D), E),
            "wo": norm_init(keys[4], (L, H, D, E), H * D),
            "mlp_norm": jnp.ones((L, E), c.dtype),
            "w_gate": norm_init(keys[5], (L, E, M), E),
            "w_up": norm_init(keys[6], (L, E, M), E),
            "w_down": norm_init(keys[7], (L, M, E), M),
        },
        "final_norm": jnp.ones((E,), c.dtype),
        "lm_head": norm_init(keys[8], (E, c.vocab_size), E),
    }


def _attention(q, k, v, config: LlamaConfig, mesh: Mesh | None):
    if config.attn_impl == "ring" and mesh is not None and mesh.shape["sp"] > 1:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(("dp", "fsdp"), "tp", "sp", None)
        fn = shard_map(
            functools.partial(ring_attention, axis="sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)
    if config.attn_impl == "reference":
        return mha_reference(q, k, v, causal=True)
    if config.attn_impl == "none":  # ablation: identity attention
        g = q.shape[1] // k.shape[1]
        return (q.reshape(q.shape[0], k.shape[1], g, *q.shape[2:]) * v[:, :, None]).reshape(q.shape)
    return flash_attention(q, k, v, causal=True)


def _block(x, layer, positions, config: LlamaConfig, mesh: Mesh | None):
    """One decoder block. x: [B, S, E] in config.dtype."""
    c = config

    def sc(t, axes):
        return shard_constraint(t, mesh, axes) if mesh is not None else t

    from jax.ad_checkpoint import checkpoint_name

    h = rms_norm(x, layer["attn_norm"], eps=c.norm_eps)
    q = jnp.einsum("bse,ehd->bhsd", h, layer["wq"])
    k = jnp.einsum("bse,ehd->bhsd", h, layer["wk"])
    v = jnp.einsum("bse,ehd->bhsd", h, layer["wv"])
    q = apply_rope(q, positions, theta=c.rope_theta)
    k = apply_rope(k, positions, theta=c.rope_theta)
    q = checkpoint_name(sc(q, ("batch", "heads", "seq", "head_dim")), "q")
    k = checkpoint_name(k, "k")
    v = checkpoint_name(v, "v")
    attn = checkpoint_name(_attention(q, k, v, c, mesh), "attn_out")
    attn_out = jnp.einsum("bhsd,hde->bse", attn, layer["wo"])
    x = x + sc(attn_out, ("batch", "seq", "embed_act"))

    h = rms_norm(x, layer["mlp_norm"], eps=c.norm_eps)
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"])
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"])
    ff = jax.nn.silu(gate.astype(jnp.float32)).astype(c.dtype) * up
    ff = sc(ff, ("batch", "seq", "mlp"))
    down = jnp.einsum("bsm,me->bse", ff, layer["w_down"])
    return x + sc(down, ("batch", "seq", "embed_act"))


def forward_hidden(params, tokens, config: LlamaConfig, *, mesh: Mesh | None = None):
    """tokens [B, S] int32 -> final hidden states [B, S, E] in config.dtype."""
    c = config
    b, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)
    x = params["embed"][tokens].astype(c.dtype)
    if mesh is not None:
        x = shard_constraint(x, mesh, ("batch", "seq", "embed_act"))

    block = functools.partial(_block, positions=positions, config=c, mesh=mesh)
    if c.remat:
        if c.remat_policy == "dots":
            block = jax.checkpoint(
                block, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            )
        elif c.remat_policy == "attn":
            # save the attention path (q/k/v projections + kernel output,
            # ~2.7 GB at 8x2048 for 1b) so the backward's recompute skips
            # the attention forward entirely — the best HBM/FLOPs trade on
            # a 16 GB chip
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "q", "k", "v", "attn_out"
                ),
            )
        else:
            block = jax.checkpoint(block)

    def scan_body(carry, layer):
        return block(carry, layer), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    return rms_norm(x, params["final_norm"], eps=c.norm_eps)


def forward(params, tokens, config: LlamaConfig, *, mesh: Mesh | None = None):
    """tokens [B, S] int32 -> logits [B, S, vocab] f32. For inference/tests;
    training uses ``loss_fn`` which never materializes full logits."""
    x = forward_hidden(params, tokens, config, mesh=mesh)
    logits = jnp.einsum("bse,ev->bsv", x, params["lm_head"])
    return logits.astype(jnp.float32)


def train_flops_per_token(config: LlamaConfig, seq: int) -> float:
    """Model FLOPs per trained token (6N matmul + causal attention), the
    numerator of MFU. Embedding gather excluded (standard accounting)."""
    c = config
    n_params = c.n_layers * (
        c.hidden * c.head_dim * (c.n_heads * 2 + c.n_kv_heads * 2)
        + 3 * c.hidden * c.intermediate
    ) + c.hidden * c.vocab_size
    attn = 6 * c.n_layers * c.n_heads * c.head_dim * seq  # causal fwd+bwd
    return 6.0 * n_params + attn


def loss_fn(
    params,
    batch,
    config: LlamaConfig,
    *,
    mesh: Mesh | None = None,
    chunk_tokens: int = 512,
):
    """Next-token cross entropy. batch: {"tokens": [B,S], "mask": [B,S]}.

    The lm_head matmul is fused into a rematerialized scan over token
    chunks so the [B,S,vocab] logits tensor never exists in HBM — at 128k
    vocab that tensor alone would OOM a v5e chip at batch 8 × 2048.
    """
    tokens = batch["tokens"]
    hidden = forward_hidden(params, tokens, config, mesh=mesh)
    targets = tokens[:, 1:]
    hidden = hidden[:, :-1]
    mask = batch.get("mask")
    mask = (jnp.ones_like(targets, jnp.float32) if mask is None
            else mask[:, 1:].astype(jnp.float32))

    b, s, e = hidden.shape
    n = b * s
    flat_h = hidden.reshape(n, e)
    flat_t = targets.reshape(n)
    flat_m = mask.reshape(n)
    chunk = min(chunk_tokens, n)
    if n % chunk:
        pad = chunk - n % chunk
        flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
        flat_t = jnp.pad(flat_t, (0, pad))
        flat_m = jnp.pad(flat_m, (0, pad))
        n += pad
    nc = n // chunk
    lm_head = params["lm_head"]

    @jax.checkpoint
    def chunk_loss(xs):
        h, t, m = xs
        logits = jnp.einsum(
            "ce,ev->cv", h, lm_head, preferred_element_type=jnp.float32
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, t[:, None], axis=-1)[:, 0] - lse
        return (ll * m).sum()

    def body(carry, xs):
        return carry + chunk_loss(xs), None

    total, _ = lax.scan(
        body,
        jnp.zeros((), jnp.float32),
        (flat_h.reshape(nc, chunk, e), flat_t.reshape(nc, chunk),
         flat_m.reshape(nc, chunk)),
    )
    return -total / jnp.maximum(flat_m.sum(), 1.0)
