"""Model zoo. Flagship: Llama-3-family decoder built TPU-first — scanned
layers, bf16 params with f32 statistics, logical-axis shardings from
``ray_tpu.parallel``, Pallas flash attention / ring attention."""

from .llama import (
    LlamaConfig,
    PRESETS,
    init_params,
    forward,
    loss_fn,
    param_axes,
)

__all__ = [
    "LlamaConfig",
    "PRESETS",
    "init_params",
    "forward",
    "loss_fn",
    "param_axes",
]
