"""Mixture-of-Experts block with expert parallelism over the ``ep`` axis.

The reference delegates expert parallelism to vLLM
(``vllm_models.py:117-168``); this is the TPU-native design: top-k routing
with a static per-expert capacity, dense one-hot dispatch/combine einsums
(no dynamic shapes — XLA turns the sharded dispatch into all-to-alls over
``ep``), experts' weights sharded on their leading axis.

Dispatch math (Switch/Mixtral style):
    router_logits [N, X]  → top-k probs
    dispatch      [N, X, C] one-hot (token n → slot c of expert x)
    expert_in  = einsum("nd,nxc->xcd", tokens, dispatch)
    expert_out = ffn(expert_in)                       # per-expert SwiGLU
    out        = einsum("xcd,nxc->nd", expert_out, combine)
Tokens over capacity C are dropped (standard capacity-factor semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_param_axes(prefix: tuple = ()):
    """Logical axes; ``prefix`` prepends e.g. ("layers",) for stacked use."""
    return {
        "router": prefix + ("embed", "experts"),
        "w_gate": prefix + ("experts", "embed", "expert_mlp"),
        "w_up": prefix + ("experts", "embed", "expert_mlp"),
        "w_down": prefix + ("experts", "expert_mlp", "embed"),
    }


def init_moe_params(key, hidden: int, expert_mlp: int, n_experts: int, dtype,
                    n_layers: int | None = None):
    """The single source of MoE init (llama.py stacks it per layer via
    ``n_layers``)."""
    ks = jax.random.split(key, 4)
    lead = () if n_layers is None else (n_layers,)

    def init(k, shape, fan_in, out_dtype=dtype):
        return (jax.random.truncated_normal(k, -2, 2, lead + shape, jnp.float32)
                * (fan_in ** -0.5)).astype(out_dtype)

    return {
        # router stays f32: routing logits are precision-sensitive
        "router": init(ks[0], (hidden, n_experts), hidden, jnp.float32),
        "w_gate": init(ks[1], (n_experts, hidden, expert_mlp), hidden),
        "w_up": init(ks[2], (n_experts, hidden, expert_mlp), hidden),
        "w_down": init(ks[3], (n_experts, expert_mlp, hidden), expert_mlp),
    }


def moe_block(x, params, *, top_k: int = 2, capacity_factor: float = 1.25,
              ep_axis: str | None = None, n_experts_global: int | None = None):
    """x: [B, S, E] → [B, S, E]. Routing in f32; expert FFN in x.dtype.

    Two execution modes:
      * jit path (``ep_axis=None``): full expert tensors; XLA lowers the
        sharded dispatch einsum into all-to-alls over ``ep``.
      * shard_map path (``ep_axis`` set, e.g. inside the pp pipeline):
        ``params`` hold only this device's expert shard; routing is global
        (router weights replicated), each device computes its local
        experts' slice of the dispatch, and a psum over ``ep`` combines.
    """
    b, s, e = x.shape
    n = b * s
    tokens = x.reshape(n, e)
    n_experts = n_experts_global or params["router"].shape[1]
    capacity = max(1, int(capacity_factor * n * top_k / n_experts))

    logits = jnp.einsum("nd,dx->nx", tokens.astype(jnp.float32), params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, top_k)  # [N, K]
    # renormalize the selected gates (Mixtral convention)
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # position of each (token, k) within its expert's capacity buffer:
    # cumulative count of earlier tokens routed to the same expert
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [N, K, X]
    flat_choice = onehot.reshape(n * top_k, n_experts)
    position = jnp.cumsum(flat_choice, axis=0) * flat_choice - 1  # [N*K, X]
    position = position.reshape(n, top_k, n_experts)
    pos_in_expert = (position * onehot).sum(-1)  # [N, K]
    keep = pos_in_expert < capacity

    # dispatch/combine tensors [N, X, C]
    cap_onehot = jax.nn.one_hot(jnp.where(keep, pos_in_expert, capacity), capacity, dtype=x.dtype)
    dispatch = jnp.einsum(
        "nkx,nkc->nxc", onehot.astype(x.dtype), cap_onehot
    )
    combine = jnp.einsum(
        "nkx,nkc,nk->nxc", onehot.astype(jnp.float32), cap_onehot.astype(jnp.float32),
        gate_vals,
    ).astype(x.dtype)

    if ep_axis is not None:
        # shard_map path: this device holds X/ep experts; slice its share
        # of the dispatch/combine and psum the partial outputs.
        x_local = params["w_gate"].shape[0]
        rank = jax.lax.axis_index(ep_axis)
        dispatch = jax.lax.dynamic_slice_in_dim(dispatch, rank * x_local, x_local, axis=1)
        combine = jax.lax.dynamic_slice_in_dim(combine, rank * x_local, x_local, axis=1)

    expert_in = jnp.einsum("nd,nxc->xcd", tokens, dispatch)  # [X, C, E]
    gate = jnp.einsum("xcd,xdm->xcm", expert_in, params["w_gate"])
    up = jnp.einsum("xcd,xdm->xcm", expert_in, params["w_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    expert_out = jnp.einsum("xcm,xmd->xcd", act, params["w_down"])
    out = jnp.einsum("xcd,nxc->nd", expert_out, combine)
    if ep_axis is not None:
        out = jax.lax.psum(out, ep_axis)
    # load-balancing aux term from the same routing probabilities
    # (Switch: X * sum(frac_tokens_to_expert * mean_prob_of_expert))
    frac_tokens = jnp.mean(onehot.astype(jnp.float32).sum(1), axis=0) / top_k
    frac_probs = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac_tokens * frac_probs)
    return out.reshape(b, s, e), aux


def moe_aux_loss(x, params, *, top_k: int = 2):
    """Load-balancing auxiliary loss (Switch: X * sum(frac_tokens * frac_probs))."""
    b, s, e = x.shape
    tokens = x.reshape(b * s, e).astype(jnp.float32)
    logits = jnp.einsum("nd,dx->nx", tokens, params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    n_experts = probs.shape[-1]
    _, expert_idx = jax.lax.top_k(probs, top_k)
    frac_tokens = jnp.mean(
        jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32).sum(1), axis=0
    ) / top_k
    frac_probs = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(frac_tokens * frac_probs)
