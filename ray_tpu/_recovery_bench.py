"""Recovery SLO suite (ROADMAP item 6): preemption as a measured event.

Two scenarios run against an in-process multi-node cluster, each driven
by the REAL preemption path (preemption notice -> raylet drain -> GCS
``node_preempted`` -> grace-window kill -> node dead) and timed with the
chaos clock:

  * **preempt-mid-train** — an async-checkpointing trainer pinned to a
    spot node; a ``preempt_slice`` FaultPlan kills the slice mid-run,
    a replacement node joins, and the controller resumes from the
    latest GCS-registered committed checkpoint. Records
    ``recovery_train_resume_s`` (notice -> first resumed report) and
    ``recovery_ckpt_lag_steps`` (steps replayed after resume).
  * **preempt-mid-serve** — a 2-replica deployment with one replica on
    the spot node; after the notice the serve controller evicts it
    proactively and traffic re-routes with zero failed requests.
    Records ``recovery_serve_reroute_s`` (notice -> eviction + table
    push) and ``recovery_serve_failed_requests``.

A scenario that cannot run records ``<metric>_skipped`` markers (honored
by ``ray_tpu.bench_check``) instead of silently vanishing. Sizes/grace
are env-tunable (``RAY_TPU_RECOVERY_BENCH_{TRAIN_STEPS,GRACE_S}``).
Standalone: ``python -m ray_tpu.cli bench recovery``.
"""

from __future__ import annotations

import os
import sys
import threading
import time

TRAIN_METRICS = ("recovery_train_resume_s", "recovery_ckpt_lag_steps")
SERVE_METRICS = ("recovery_serve_reroute_s",)


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


def _wait_for(predicate, timeout: float, interval: float = 0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    return predicate()


def _fresh_shutdown() -> None:
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def _notice_clock(timeout: float = 30.0) -> float | None:
    """Chaos-clock stamp of the first node_preempted ErrorEvent."""
    from ray_tpu.util import state

    events = _wait_for(
        lambda: state.list_errors(error_type="node_preempted", limit=100),
        timeout)
    if not events:
        return None
    return float((events[0].get("extra") or {}).get("notice_clock") or 0.0)


def run_train_scenario(train_steps: int, grace_s: float,
                       storage: str) -> dict:
    import ray_tpu
    from ray_tpu import chaos, train
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import (CheckpointConfig, DataParallelTrainer,
                               FailureConfig, RunConfig, ScalingConfig)

    _fresh_shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4},
                _system_config={"health_check_period_ms": 200,
                                "preempt_grace_s": grace_s})
    spot = c.add_node(num_cpus=2, resources={"spot_slice": 1.0})
    ray_tpu.init(address=c.address, num_cpus=0)
    every_n = 2
    out: dict = {}
    try:
        def train_fn(config):
            import time as _t

            import numpy as np

            from ray_tpu import train as tr
            from ray_tpu.resilience import load_checkpoint

            start = 0
            ck = tr.get_checkpoint()
            if ck is not None:
                tree, _meta = load_checkpoint(ck.path)
                start = int(tree["step"]) + 1
            for step in range(start, config["steps"]):
                tr.report({"step": step, "loss": 1.0 / (1.0 + step)},
                          state={"step": step,
                                 "w": np.full(1024, float(step),
                                              dtype=np.float32)})
                _t.sleep(0.1)

        trainer = DataParallelTrainer(
            train_fn,
            train_loop_config={"steps": train_steps},
            scaling_config=ScalingConfig(
                num_workers=1,
                resources_per_worker={"CPU": 1.0, "spot_slice": 1.0}),
            run_config=RunConfig(
                name="recovery_bench", storage_path=storage,
                checkpoint_config=CheckpointConfig(
                    async_save=True, every_n_steps=every_n, num_to_keep=3),
                failure_config=FailureConfig(max_failures=3)),
        )
        box: dict = {}
        t = threading.Thread(target=lambda: box.update(result=trainer.fit()))
        t.start()
        # Inject only once training is underway AND committed at least one
        # checkpoint — the preemption must provably land MID-train.
        from ray_tpu.resilience import latest_registered

        if not _wait_for(lambda: latest_registered("recovery_bench"),
                         timeout=120.0):
            raise TimeoutError("no async checkpoint was ever registered")
        chaos.install({
            "name": "bench-preempt-train",
            "faults": [{"kind": "preempt_slice", "nth": 3,
                        "max_injections": 1,
                        "node": spot.node_id.hex()[:16]}],
        }, seed=0, publish=False)
        notice = _notice_clock(timeout=60.0)
        # the replacement slice the autoscaler would launch
        c.add_node(num_cpus=2, resources={"spot_slice": 1.0})
        t.join(timeout=240.0)
        if t.is_alive() or notice is None:
            raise TimeoutError("train scenario did not finish")
        result = box["result"]
        if result.error is not None:
            raise RuntimeError(f"train run failed: {result.error}")
        resumed = [e for e in result.recovery_events
                   if e.get("resumed_clock") is not None]
        if not resumed:
            raise RuntimeError("no recovery event was stamped")
        out["recovery_train_resume_s"] = round(
            max(0.0, resumed[0]["resumed_clock"] - notice), 3)
        steps = [m["step"] for m in result.metrics_history]
        replayed = 0
        for prev, cur in zip(steps, steps[1:]):
            if cur <= prev:  # the restart point: overlap = replayed work
                replayed = prev - cur + 1
        out["recovery_ckpt_lag_steps"] = replayed
        if replayed > every_n:
            out["recovery_ckpt_lag_warning"] = (
                f"lag {replayed} > every_n_steps {every_n}")
        if steps[-1] != train_steps - 1:
            raise RuntimeError(f"run did not reach step {train_steps - 1}")
    finally:
        try:
            chaos.uninstall()
        except Exception:
            pass
        _fresh_shutdown()
        c.shutdown()
    return out


def run_serve_scenario(grace_s: float) -> dict:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    _fresh_shutdown()
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 4,
                                "resources": {"replica_slot": 1.0}},
                _system_config={"health_check_period_ms": 200,
                                "preempt_grace_s": grace_s})
    spot = c.add_node(num_cpus=2, resources={"replica_slot": 1.0})
    ray_tpu.init(address=c.address, num_cpus=0)
    out: dict = {}
    try:
        @serve.deployment(num_replicas=2, ray_actor_options={
            "num_cpus": 0.1, "resources": {"replica_slot": 1.0}})
        class Echo:
            def hello(self, x):
                return f"hello {x}"

        handle = serve.run(Echo.bind(), name="recovery_bench_app",
                           route_prefix=None, _blocking=False)
        ready = _wait_for(
            lambda: (serve.status().get("recovery_bench_app", {})
                     .get("Echo", {}).get("running_replicas") == 2),
            timeout=120.0)
        if not ready:
            raise TimeoutError("2 replicas never became ready")
        # Preempt a node hosting a replica but NOT the serve controller —
        # the controller must survive to run the proactive eviction (in
        # production the controller would be restarted elsewhere first).
        from ray_tpu.util import state as st

        ctrl_node = next((a.get("node_id") for a in st.list_actors()
                          if a.get("name") == "SERVE_CONTROLLER"), "")
        victim = c.head_node if spot.node_id.hex() == ctrl_node else spot
        # long grace: the PROACTIVE eviction, not the eventual death,
        # must do the re-routing
        c._loop.run_sync(victim.handle_PreemptionNotice(
            {"reason": "bench spot reclaim", "grace_s": max(5.0, grace_s)}))
        failures = 0
        for i in range(40):
            try:
                if handle.hello.remote(i).result(timeout=30) != f"hello {i}":
                    failures += 1
            except Exception:
                failures += 1
            time.sleep(0.05)
        evictions = _wait_for(
            lambda: (serve.status().get("recovery_bench_app", {})
                     .get("Echo", {}).get("preemption_evictions")),
            timeout=30.0)
        if not evictions:
            raise RuntimeError("no proactive preemption eviction recorded")
        out["recovery_serve_reroute_s"] = round(
            float(evictions[0]["reroute_s"]), 3)
        out["recovery_serve_failed_requests"] = failures
    finally:
        try:
            serve.delete("recovery_bench_app")
        except Exception:
            pass
        try:
            serve.shutdown()
        except Exception:
            pass
        _fresh_shutdown()
        c.shutdown()
    return out


def run_recovery_bench(train_steps: int | None = None,
                       grace_s: float | None = None) -> dict:
    train_steps = train_steps or _env_int(
        "RAY_TPU_RECOVERY_BENCH_TRAIN_STEPS", 24)
    grace_s = grace_s or _env_float("RAY_TPU_RECOVERY_BENCH_GRACE_S", 0.5)
    import tempfile

    out: dict = {"recovery_grace_cfg": grace_s}
    try:
        with tempfile.TemporaryDirectory(prefix="raytpu-recovery-") as d:
            out.update(run_train_scenario(train_steps, grace_s, d))
    except Exception as e:
        print(f"recovery train scenario failed: {e}", file=sys.stderr)
        out["recovery_train_error"] = f"{type(e).__name__}: {e}"
        for m in TRAIN_METRICS:
            out[f"{m}_skipped"] = True
    try:
        out.update(run_serve_scenario(grace_s))
    except Exception as e:
        print(f"recovery serve scenario failed: {e}", file=sys.stderr)
        out["recovery_serve_error"] = f"{type(e).__name__}: {e}"
        for m in SERVE_METRICS:
            out[f"{m}_skipped"] = True
    return out


if __name__ == "__main__":
    import json

    print(json.dumps(run_recovery_bench(), indent=2))
