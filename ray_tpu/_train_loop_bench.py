"""Train compiled-loop suite (ROADMAP item 6).

Measures what parking the train step on the persistent compiled loop
(``train/loop.py``) buys over per-step dynamic dispatch — the train-side
mirror of the dag bench's dynamic-vs-compiled cells:

  * **Step dispatch overhead** — a NO-OP structured step driven (a)
    eagerly (one ``.remote()`` chain per step: the submit→lease→push
    path every iteration) and (b) through the compiled loop (channel
    write + read, zero task submission):

      - ``train_step_dispatch_overhead_eager_us`` — eager per-step µs
      - ``train_step_dispatch_overhead_us``       — compiled per-step µs
        (acceptance: ≥ 5× below eager on the CPU sandbox)

  * **Train MFU, eager vs loop** — a real (small-model) jax train step
    with async checkpoint snapshots every N steps, driven both ways
    through the SAME stage actors (byte-identical math — the parity
    contract is tested in tests/test_train_loop.py):

      - ``train_mfu_eager`` / ``train_mfu_loop`` — loop must be ≥ eager
        (the loop removes per-step dispatch AND overlaps the commit)
      - ``train_ckpt_overlap_frac`` — fraction of checkpoint-commit
        wall time that overlapped step compute in loop mode
        (acceptance: > 0.5; structurally 0 in eager mode)
      - ``train_loop_ckpt_save_block_ms`` — max snapshot block inside
        the step stage (must stay flat vs eager: the step never waits
        for the writer)

``RAY_TPU_BENCH_SKIP_TRAIN_LOOP=1`` records ``*_skipped`` markers
instead (bench_check treats the absence as intentional). Sizes are
env-tunable via ``RAY_TPU_TRAIN_LOOP_BENCH_{TICKS,STEPS}``. Run
standalone via ``python -m ray_tpu.cli bench train --loop`` or as part
of ``bench.py``.

CPU-sandbox honesty: the MFU cells here use the debug-128 model against
the v5e peak, so their absolute values are tiny — the guarded signal is
the eager↔loop RATIO and the overlap fraction; on-chip absolute cells
ride the next BENCH (ROADMAP item 1b).
"""

from __future__ import annotations

import os

PEAK_FLOPS = 197e12  # same denominator as bench.py / session gauges

_SKIP_MARKERS = {
    "train_mfu_skipped": True,
    "train_step_dispatch_overhead_skipped": True,
    "train_ckpt_overlap_frac_skipped": True,
}


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _noop_spec(num_steps: int):
    from ray_tpu.train import TrainLoopConfig

    def init_fn(config):
        return {"count": 0}

    def step_fn(state, batch):
        c = state["count"] + 1
        return {"count": c}, {"count": c}

    return TrainLoopConfig(step_fn=step_fn, init_fn=init_fn,
                           num_steps=num_steps, snapshot_every=0, credits=4)


def _model_spec(num_steps: int, batch: int, seq: int, snapshot_every: int,
                preset: str):
    """A real forward+backward SGD step on the debug llama config; the
    jitted step is cached in a closure cell so it compiles once per
    stage actor, not once per tick."""
    from ray_tpu.train import TrainLoopConfig

    cache: dict = {}

    def init_fn(config):
        import jax

        from ray_tpu.models.llama import PRESETS, init_params

        return {"params": init_params(PRESETS[preset],
                                      jax.random.PRNGKey(0)),
                "count": 0}

    def data_fn(config):
        import numpy as np

        from ray_tpu.models.llama import PRESETS

        vocab = PRESETS[preset].vocab_size

        def gen():
            rng = np.random.default_rng(0)
            while True:
                yield rng.integers(0, vocab, (batch, seq + 1),
                                   dtype=np.int32)
        return gen()

    def step_fn(state, tokens):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import PRESETS, forward

        if "step" not in cache:
            cfg = PRESETS[preset]

            def loss_fn(params, x, y):
                logits = forward(params, x, cfg).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(
                    logp, y[..., None], axis=-1).mean()

            @jax.jit
            def sgd(params, x, y):
                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                new = jax.tree.map(lambda p, g: p - 1e-3 * g, params, grads)
                return new, loss

            cache["step"] = sgd
        params, loss = cache["step"](state["params"],
                                     tokens[:, :-1], tokens[:, 1:])
        c = state["count"] + 1
        return ({"params": params, "count": c},
                {"loss": float(loss), "count": c})

    # credits 8: the step stage must be able to run a full
    # snapshot interval ahead while the committer works, or the ring
    # backpressure serializes exactly the overlap this mode exists for.
    return TrainLoopConfig(step_fn=step_fn, init_fn=init_fn, data_fn=data_fn,
                           num_steps=num_steps,
                           snapshot_every=snapshot_every, credits=8,
                           channel_capacity=8 << 20)


def _overlap_spec(num_steps: int, snapshot_every: int):
    """Device-proxy step for the overlap cell: the step WAITS (as a TPU
    train step does from the host's perspective — compute runs on the
    chip) while carrying a real few-MB state, so the checkpoint stage's
    commit can genuinely run during it. On the 1-core sandbox a
    CPU-saturating step and the commit cannot physically overlap — the
    MFU phase covers that contention case; this phase measures the
    MECHANISM the mode exists for (host commit under device compute)."""
    from ray_tpu.train import TrainLoopConfig

    def init_fn(config):
        import numpy as np

        return {"w": np.zeros(1 << 18), "count": 0}

    def step_fn(state, batch):
        import time as _t

        _t.sleep(0.25)
        c = state["count"] + 1
        return {"w": state["w"] + 1.0, "count": c}, {"count": c}

    return TrainLoopConfig(step_fn=step_fn, init_fn=init_fn,
                           num_steps=num_steps,
                           snapshot_every=snapshot_every, credits=4,
                           channel_capacity=8 << 20)


def _fit(spec, name: str, use_loop: bool, storage: str):
    from ray_tpu.train import (DataParallelTrainer, RunConfig,
                               ScalingConfig)

    trainer = DataParallelTrainer(
        spec,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name=name, storage_path=storage),
        use_compiled_loop=use_loop,
    )
    result = trainer.fit()
    if result.error is not None:
        raise RuntimeError(f"train-loop bench run {name!r} failed: "
                           f"{result.error}")
    return result.loop_stats


def run_train_loop_bench(*, ticks: int | None = None,
                         steps: int | None = None,
                         connect: bool = True) -> dict:
    """Run both phases and return the metrics dict (or the ``*_skipped``
    markers under ``RAY_TPU_BENCH_SKIP_TRAIN_LOOP=1``)."""
    if os.environ.get("RAY_TPU_BENCH_SKIP_TRAIN_LOOP") == "1":
        return dict(_SKIP_MARKERS)
    import tempfile

    import ray_tpu

    ticks = ticks or _env_int("RAY_TPU_TRAIN_LOOP_BENCH_TICKS", 150)
    steps = steps or _env_int("RAY_TPU_TRAIN_LOOP_BENCH_STEPS", 24)
    batch, seq, preset = 2, 64, "debug-128"
    out: dict = {}
    if connect:
        ray_tpu.init(num_cpus=max(8, os.cpu_count() or 8),
                     ignore_reinit_error=True)
    storage = tempfile.mkdtemp(prefix="raytpu_train_loop_bench_")
    try:
        # Phase 1: dispatch overhead, no-op step (the step cost is the
        # drive path itself). Steady-state per-step wall (end of step 0
        # → end of the last step) keeps actor spawn, first-call export
        # and the loop's one-time channel setup off the measurement —
        # the dag bench's warm-then-time discipline.
        eager = _fit(_noop_spec(ticks), "tlb_dispatch_eager", False, storage)
        loop = _fit(_noop_spec(ticks), "tlb_dispatch_loop", True, storage)
        out["train_step_dispatch_overhead_eager_us"] = \
            eager["steady_step_wall_us"]
        out["train_step_dispatch_overhead_us"] = loop["steady_step_wall_us"]

        # Phase 2: real-model MFU cells, steady window again (the first
        # step's jit compile would otherwise dominate a CPU-sandbox run
        # in both modes). Snapshots are OFF here so the pair isolates
        # the per-step DRIVE delta — the checkpoint dimension has its
        # own phase below; folding a ±300 ms orbax commit into a 30 ms
        # step measurement buries the guarded signal in commit noise.
        from ray_tpu.models.llama import PRESETS, train_flops_per_token

        flops_tok = train_flops_per_token(PRESETS[preset], seq)

        def tok_s(stats) -> float:
            return (batch * seq * stats["steady_steps"]
                    / max(stats["steady_wall_s"], 1e-9))

        def mfu(stats) -> float:
            return round(tok_s(stats) * flops_tok / PEAK_FLOPS, 8)

        e_stats = _fit(_model_spec(steps, batch, seq, 0, preset),
                       "tlb_mfu_eager", False, storage)
        l_stats = _fit(_model_spec(steps, batch, seq, 0, preset),
                       "tlb_mfu_loop", True, storage)
        out["train_mfu_eager"] = mfu(e_stats)
        out["train_mfu_loop"] = mfu(l_stats)
        out["train_eager_tok_s"] = round(tok_s(e_stats), 1)
        out["train_loop_tok_s"] = round(tok_s(l_stats), 1)

        # Phase 3: checkpoint-commit cells under a device-proxy step
        # (see _overlap_spec — the host-side commit must ride UNDER the
        # step, which on a chip runs on the device). Both drive modes on
        # the identical workload: the loop's overlap fraction is the
        # guarded cell, and the step-side snapshot block must stay flat
        # across modes (the step never waits for the writer).
        o_eager = _fit(_overlap_spec(16, 4), "tlb_overlap_eager", False,
                       storage)
        o_loop = _fit(_overlap_spec(16, 4), "tlb_overlap_loop", True,
                      storage)
        out["train_ckpt_overlap_frac"] = o_loop["train_ckpt_overlap_frac"]
        out["train_loop_ckpt_save_block_ms"] = o_loop["ckpt_save_block_ms"]
        out["train_eager_ckpt_save_block_ms"] = o_eager["ckpt_save_block_ms"]
        out["train_loop_bench_ticks_cfg"] = ticks
        out["train_loop_bench_steps_cfg"] = steps
    finally:
        if connect:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass
    return out


if __name__ == "__main__":
    import json

    print(json.dumps(run_train_loop_bench(), indent=2))
