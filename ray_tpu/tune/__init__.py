"""ray_tpu.tune: hyperparameter search.

Reference: ``python/ray/tune/`` (SURVEY.md §2.3): Tuner.fit over trial
actors with searchers (grid/random) and schedulers (ASHA, PBT).
``tune.report`` shares the Train session plumbing — a trial is a
one-worker train run.
"""

from ..train.session import get_checkpoint, get_context, report
from .callback import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    TBXLoggerCallback,
)
from .schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from .search import (
    BasicVariantGenerator,
    OptunaSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from .tuner import ResultGrid, TuneConfig, Tuner

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "ASHAScheduler",
    "Callback",
    "CSVLoggerCallback",
    "JsonLoggerCallback",
    "TBXLoggerCallback",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "FIFOScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "OptunaSearch",
    "Searcher",
    "TPESearcher",
    "ResultGrid",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_context",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "uniform",
]
