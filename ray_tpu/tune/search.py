"""Search spaces and suggestion algorithms.

Reference: ``python/ray/tune/search/`` — sample-space primitives
(``tune.uniform``/``choice``/``grid_search``) and the default
``BasicVariantGenerator`` (grid expansion × random sampling). External
searchers (Optuna/HyperOpt/...) are separate pip packages in the
reference; here they gate on import availability.
"""

from __future__ import annotations

import itertools
import random
from typing import Any


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class TPESearcher:
    """Sequential model-based search, Tree-structured Parzen Estimator
    style (the role Optuna's default sampler plays behind the reference's
    ``OptunaSearch``, ``tune/search/optuna/optuna_search.py:81`` — Optuna
    itself is not available in this image, so the estimator is native).

    ``suggest()`` proposes configs one at a time; completed trials are fed
    back via ``on_trial_complete``. Numeric params: candidates are drawn
    from a Parzen window over the top-``gamma`` configs and ranked by the
    good/bad density ratio. Categoricals: weighted by goodness counts.
    Falls back to random sampling until ``n_startup`` observations exist.
    """

    def __init__(self, metric: str, mode: str = "max", *, seed: int | None = None,
                 gamma: float = 0.25, n_startup: int = 6, n_candidates: int = 24):
        self.metric = metric
        self.sign = 1.0 if mode == "max" else -1.0
        self._rng = random.Random(seed)
        self.gamma = gamma
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self._observations: list[tuple[dict, float]] = []
        self._space: dict | None = None

    # --------------------------------------------------- sequential protocol
    def set_space(self, param_space: dict) -> None:
        self._space = {
            k: (GridSearch(v["grid_search"])
                if isinstance(v, dict) and set(v) == {"grid_search"} else v)
            for k, v in param_space.items()
        }

    def on_trial_complete(self, config: dict, metrics: dict) -> None:
        if metrics and self.metric in metrics:
            self._observations.append((config, self.sign * float(metrics[self.metric])))

    def suggest(self) -> dict:
        assert self._space is not None, "set_space() first"
        if len(self._observations) < self.n_startup:
            return self._random_config()
        ranked = sorted(self._observations, key=lambda o: -o[1])
        n_good = max(1, int(len(ranked) * self.gamma))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        best, best_score = None, float("-inf")
        for _ in range(self.n_candidates):
            cand = self._sample_near(good)
            score = self._density(cand, good) - self._density(cand, bad)
            if score > best_score:
                best, best_score = cand, score
        return best

    # ------------------------------------------------------------- internals
    def _numeric_value(self, key, value) -> float | None:
        dom = self._space[key]
        import math

        if isinstance(dom, (Uniform, RandInt)):
            return float(value)
        if isinstance(dom, LogUniform):
            return math.log(max(value, 1e-300))
        return None

    def _random_config(self) -> dict:
        cfg = {}
        for k, v in self._space.items():
            if isinstance(v, GridSearch):
                cfg[k] = self._rng.choice(v.values)
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self._rng)
            else:
                cfg[k] = v
        return cfg

    def _domain_range(self, dom) -> tuple[float, float]:
        if isinstance(dom, Uniform):
            return dom.low, dom.high
        if isinstance(dom, RandInt):
            return float(dom.low), float(dom.high)
        return dom._lo, dom._hi  # LogUniform: log domain

    def _bandwidth(self, xs: list[float], dom) -> float:
        lo, hi = self._domain_range(dom)
        # Parzen bandwidth: shrinks as evidence accumulates (Scott-rule
        # style n^-1/5) but with a PRIOR FLOOR so a collapsed good-set
        # never freezes the search (TPE mixes the uniform prior in).
        n = max(len(self._observations), 1)
        return max((max(xs) - min(xs)) * 0.5,
                   (hi - lo) / 8.0 * n ** -0.2)

    def _sample_near(self, good: list[dict]) -> dict:
        import math

        cfg = {}
        for k, dom in self._space.items():
            if not isinstance(dom, Domain) and not isinstance(dom, GridSearch):
                cfg[k] = dom
                continue
            cats = dom.values if isinstance(dom, GridSearch) else (
                dom.categories if isinstance(dom, Choice) else None)
            if cats is not None:
                # categorical: sample weighted by goodness counts (+1 prior)
                weights = [1 + sum(1 for g in good if g.get(k) == c) for c in cats]
                cfg[k] = self._rng.choices(cats, weights=weights)[0]
                continue
            if self._rng.random() < 0.35:
                # exploration: draw from the prior (TPE's prior mixture)
                cfg[k] = dom.sample(self._rng)
                continue
            xs = [self._numeric_value(k, g[k]) for g in good if k in g]
            # rank-weighted anchor: the BEST point (good[0]) pulls hardest
            anchor = xs[0] if self._rng.random() < 0.5 else self._rng.choice(xs)
            x = self._rng.gauss(anchor, self._bandwidth(xs, dom))
            if isinstance(dom, Uniform):
                cfg[k] = min(max(x, dom.low), dom.high)
            elif isinstance(dom, RandInt):
                cfg[k] = int(min(max(round(x), dom.low), dom.high - 1))
            else:  # LogUniform
                cfg[k] = min(max(math.exp(x), math.exp(dom._lo)), math.exp(dom._hi))
        return cfg

    def _density(self, cand: dict, configs: list[dict]) -> float:
        import math

        total = 0.0
        for k, dom in self._space.items():
            if isinstance(dom, GridSearch) or isinstance(dom, Choice):
                cats = dom.values if isinstance(dom, GridSearch) else dom.categories
                count = sum(1 for c in configs if c.get(k) == cand[k])
                total += math.log((count + 1) / (len(configs) + len(cats)))
            elif isinstance(dom, Domain):
                xs = [self._numeric_value(k, c[k]) for c in configs if k in c]
                if not xs:
                    continue
                x = self._numeric_value(k, cand[k])
                bw = self._bandwidth(xs, dom)
                total += math.log(sum(
                    math.exp(-0.5 * ((x - xi) / bw) ** 2) for xi in xs
                ) / len(xs) + 1e-12)
        return total


class BasicVariantGenerator:
    """Grid axes are expanded exhaustively; Domain axes sampled num_samples
    times. Reference: search/basic_variant.py."""

    def __init__(self, *, seed: int | None = None):
        self._rng = random.Random(seed)

    def generate(self, param_space: dict, num_samples: int) -> list[dict]:
        # Reference-compatible dict form: {"grid_search": [...]}.
        param_space = {
            k: (GridSearch(v["grid_search"])
                if isinstance(v, dict) and set(v) == {"grid_search"} else v)
            for k, v in param_space.items()
        }
        grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
        grids = [param_space[k].values for k in grid_keys]
        configs: list[dict] = []
        grid_combos = list(itertools.product(*grids)) if grid_keys else [()]
        for _ in range(num_samples):
            for combo in grid_combos:
                cfg = {}
                for k, v in param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                configs.append(cfg)
        return configs


class Searcher:
    """Sequential-searcher protocol the controller drives (reference
    ``tune/search/searcher.py``): ``set_space`` once, then alternate
    ``suggest`` / ``on_trial_complete``. ``TPESearcher`` is the native
    implementation; ``OptunaSearch`` adapts an external library through
    the same three methods — write an adapter with this surface to plug
    in any external optimizer (HyperOpt/Ax/BOHB equivalents)."""

    def set_space(self, param_space: dict) -> None:
        raise NotImplementedError

    def suggest(self) -> dict:
        raise NotImplementedError

    def on_trial_complete(self, config: dict, metrics: dict) -> None:
        raise NotImplementedError


class OptunaSearch(Searcher):
    """Adapter over Optuna's ask/tell interface (reference
    ``tune/search/optuna/optuna_search.py``): Domain objects map to
    Optuna distributions; each ``suggest`` asks a trial, each completion
    tells its objective value. Requires ``optuna`` (not bundled in this
    image — the import is deferred and raises a clear error)."""

    def __init__(self, metric: str, mode: str = "max", *, seed: int | None = None,
                 sampler=None):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the `optuna` package; install it or "
                "use the native TPESearcher (same protocol)") from e
        self._optuna = optuna
        self.metric = metric
        self._direction = "maximize" if mode == "max" else "minimize"
        self._sampler = sampler or optuna.samplers.TPESampler(seed=seed)
        self._study = None
        self._space: dict = {}
        self._live: dict[int, Any] = {}  # config-id -> optuna trial

    def set_space(self, param_space: dict) -> None:
        optuna = self._optuna
        self._study = optuna.create_study(
            direction=self._direction, sampler=self._sampler)
        dist = optuna.distributions
        self._space = {}
        for k, v in param_space.items():
            if isinstance(v, Uniform):
                self._space[k] = dist.FloatDistribution(v.low, v.high)
            elif isinstance(v, LogUniform):
                self._space[k] = dist.FloatDistribution(v.low, v.high, log=True)
            elif isinstance(v, RandInt):
                self._space[k] = dist.IntDistribution(v.low, v.high - 1)
            elif isinstance(v, Choice):
                self._space[k] = dist.CategoricalDistribution(list(v.categories))
            elif isinstance(v, GridSearch):
                self._space[k] = dist.CategoricalDistribution(list(v.values))
            else:
                self._space[k] = dist.CategoricalDistribution([v])

    @staticmethod
    def _key(config: dict):
        return tuple(sorted((k, repr(v)) for k, v in config.items()))

    def suggest(self) -> dict:
        trial = self._study.ask(self._space)
        config = dict(trial.params)
        # identical configs may be suggested twice: a list per key
        self._live.setdefault(self._key(config), []).append(trial)
        return config

    def on_trial_complete(self, config: dict, metrics: dict) -> None:
        trials = self._live.get(self._key(config))
        if trials and metrics and self.metric in metrics:
            self._study.tell(trials.pop(0), float(metrics[self.metric]))
