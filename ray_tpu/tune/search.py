"""Search spaces and suggestion algorithms.

Reference: ``python/ray/tune/search/`` — sample-space primitives
(``tune.uniform``/``choice``/``grid_search``) and the default
``BasicVariantGenerator`` (grid expansion × random sampling). External
searchers (Optuna/HyperOpt/...) are separate pip packages in the
reference; here they gate on import availability.
"""

from __future__ import annotations

import itertools
import random
from typing import Any


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class BasicVariantGenerator:
    """Grid axes are expanded exhaustively; Domain axes sampled num_samples
    times. Reference: search/basic_variant.py."""

    def __init__(self, *, seed: int | None = None):
        self._rng = random.Random(seed)

    def generate(self, param_space: dict, num_samples: int) -> list[dict]:
        # Reference-compatible dict form: {"grid_search": [...]}.
        param_space = {
            k: (GridSearch(v["grid_search"])
                if isinstance(v, dict) and set(v) == {"grid_search"} else v)
            for k, v in param_space.items()
        }
        grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
        grids = [param_space[k].values for k in grid_keys]
        configs: list[dict] = []
        grid_combos = list(itertools.product(*grids)) if grid_keys else [()]
        for _ in range(num_samples):
            for combo in grid_combos:
                cfg = {}
                for k, v in param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    else:
                        cfg[k] = v
                configs.append(cfg)
        return configs
