"""Tuner + TuneController: concurrent trial execution with schedulers.

Reference: ``python/ray/tune/tuner.py:312`` (Tuner.fit) →
``execution/tune_controller.py:68`` (step:666). Trials run as actors
(the Train worker actor is reused — a trial is a one-worker train run);
the controller polls results, feeds searcher/scheduler, and enforces
stop decisions. PBT restarts trials in place with exploited configs.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

from ..core import api as ray
from ..train.checkpoint import Checkpoint, CheckpointManager
from ..train.config import CheckpointConfig, Result, RunConfig
from ..train.worker_group import TrainWorker
from .callback import CallbackList
from .schedulers import CONTINUE, STOP, FIFOScheduler, PopulationBasedTraining
from .search import BasicVariantGenerator


@dataclasses.dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    search_alg: Any = None
    seed: int | None = None


class Trial:
    _counter = 0

    def __init__(self, config: dict, trial_dir: str):
        Trial._counter += 1
        self.trial_id = f"trial_{Trial._counter:05d}"
        self.config = config
        self.dir = trial_dir
        self.actor = None
        self.state = "PENDING"
        self.last_metrics: dict | None = None
        self.metrics_history: list[dict] = []
        self.error: str | None = None
        self.ckpt_manager: CheckpointManager | None = None
        self.resume_path: str | None = None


class ResultGrid:
    def __init__(self, results: list[Result]):
        self._results = results

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None, mode: str = "max") -> Result:
        sign = 1.0 if mode == "max" else -1.0
        scored = [r for r in self._results if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return max(scored, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics or {} for r in self._results])


class Tuner:
    def __init__(
        self,
        trainable: Callable[[dict], None],
        *,
        param_space: dict | None = None,
        tune_config: TuneConfig | None = None,
        run_config: RunConfig | None = None,
    ):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore_dir: str | None = None

    @classmethod
    def restore(cls, path: str, trainable: Callable[[dict], None], *,
                tune_config: TuneConfig | None = None,
                run_config: RunConfig | None = None) -> "Tuner":
        """Reattach to an interrupted experiment (reference
        ``Tuner.restore``): completed trials keep their results; pending,
        running, and errored trials re-run, resuming from their latest
        checkpoint when one was registered. Pass the SAME tune_config /
        run_config as the original run — scheduler and checkpoint policy
        are code, not persisted state (defaults: FIFO scheduler, default
        checkpoint retention)."""
        tuner = cls(trainable, tune_config=tune_config, run_config=run_config)
        tuner._restore_dir = path
        return tuner

    # ------------------------------------------------------- state snapshot
    @staticmethod
    def _save_experiment_state(exp_dir: str, trials: list[Trial]) -> None:
        import cloudpickle

        state = [
            {
                "trial_id": t.trial_id,
                "config": t.config,
                "dir": t.dir,
                "state": t.state,
                "last_metrics": t.last_metrics,
                "metrics_history": t.metrics_history,
                "error": t.error,
                "latest_checkpoint": (
                    t.ckpt_manager.latest.path
                    if t.ckpt_manager and t.ckpt_manager.latest else None
                ),
            }
            for t in trials
        ]
        tmp = os.path.join(exp_dir, "experiment_state.pkl.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))

    def _load_trials_for_restore(self, ckpt_cfg) -> list[Trial]:
        import pickle

        with open(os.path.join(self._restore_dir, "experiment_state.pkl"), "rb") as f:
            state = pickle.load(f)
        trials = []
        for entry in state:
            t = Trial(entry["config"], entry["dir"])
            t.trial_id = entry["trial_id"]
            t.metrics_history = entry["metrics_history"]
            t.last_metrics = entry["last_metrics"]
            t.ckpt_manager = CheckpointManager(ckpt_cfg)
            if entry["latest_checkpoint"] and os.path.exists(entry["latest_checkpoint"]):
                t.ckpt_manager.register(
                    Checkpoint(entry["latest_checkpoint"]), entry["last_metrics"] or {}
                )
            if entry["state"] == "TERMINATED" and not entry["error"]:
                t.state = "TERMINATED"  # keep its result; don't re-run
            else:
                t.state = "PENDING"
                t.error = None
                t.resume_path = entry["latest_checkpoint"]
                # Fresh attempt: stale history would double-count and make
                # schedulers see training_iteration jump backwards.
                t.metrics_history = []
                t.last_metrics = None
            trials.append(t)
        return trials

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        ckpt_cfg = self._run_config.checkpoint_config or CheckpointConfig()
        callbacks = CallbackList(getattr(self._run_config, "callbacks", None))
        searcher = None  # sequential (suggest/on_trial_complete) searcher
        to_suggest = 0
        if self._restore_dir is not None:
            exp_dir = self._restore_dir
            name = os.path.basename(exp_dir.rstrip("/"))
            trials = self._load_trials_for_restore(ckpt_cfg)
            scheduler = tc.scheduler or FIFOScheduler()
        else:
            name = self._run_config.name or f"tune_{int(time.time())}"
            storage = self._run_config.storage_path or "/tmp/ray_tpu/results"
            exp_dir = os.path.join(storage, name)
            os.makedirs(exp_dir, exist_ok=True)

            search = tc.search_alg or BasicVariantGenerator(seed=tc.seed)
            scheduler = tc.scheduler or FIFOScheduler()
            if hasattr(search, "suggest"):
                # Sequential model-based search (TPE/BO): configs are
                # proposed one at a time, informed by completed trials
                # (reference: SearchGenerator over a Searcher).
                searcher = search
                searcher.set_space(self._param_space)
                to_suggest = tc.num_samples
                trials = []
            else:
                configs = search.generate(self._param_space, tc.num_samples)
                trials = [
                    Trial(cfg, os.path.join(exp_dir, f"trial_{i:05d}"))
                    for i, cfg in enumerate(configs)
                ]
                for t in trials:
                    os.makedirs(t.dir, exist_ok=True)
                    t.ckpt_manager = CheckpointManager(ckpt_cfg)
        self._save_experiment_state(exp_dir, trials)
        callbacks.setup(experiment_dir=exp_dir)

        def new_trial(cfg: dict) -> Trial:
            t = Trial(cfg, os.path.join(exp_dir, f"trial_{len(trials):05d}"))
            os.makedirs(t.dir, exist_ok=True)
            t.ckpt_manager = CheckpointManager(ckpt_cfg)
            trials.append(t)
            return t

        pending = [t for t in trials if t.state == "PENDING"]
        running: list[Trial] = []
        worker_cls = ray.remote(TrainWorker)

        def start(trial: Trial) -> None:
            trial.actor = worker_cls.options(name=f"tune_{name}_{trial.trial_id}_{time.monotonic_ns()}").remote(
                0, 1, trial.trial_id, trial.dir
            )
            ray.get(
                trial.actor.run_train_fn.remote(self._trainable, trial.config, trial.resume_path),
                timeout=60,
            )
            trial.state = "RUNNING"
            callbacks.on_trial_start(trial)

        def finish(trial: Trial) -> None:
            nonlocal to_suggest
            if searcher is not None:
                searcher.on_trial_complete(trial.config, trial.last_metrics)
            if trial.state == "ERROR":
                callbacks.on_trial_error(trial)
            else:
                callbacks.on_trial_complete(trial)

        try:
            while pending or running or to_suggest > 0:
                while to_suggest > 0 and len(running) + len(pending) < tc.max_concurrent_trials:
                    pending.append(new_trial(searcher.suggest()))
                    to_suggest -= 1
                while pending and len(running) < tc.max_concurrent_trials:
                    trial = pending.pop(0)
                    start(trial)
                    running.append(trial)

                time.sleep(0.1)
                for trial in list(running):
                    try:
                        poll = ray.get(trial.actor.poll.remote(), timeout=30)
                    except Exception as e:
                        trial.state = "ERROR"
                        trial.error = str(e)
                        running.remove(trial)
                        finish(trial)
                        continue
                    decision = CONTINUE
                    for entry in poll["reports"]:
                        metrics = entry["metrics"]
                        trial.last_metrics = metrics
                        trial.metrics_history.append(metrics)
                        callbacks.on_trial_result(trial, metrics)
                        if "checkpoint_path" in entry:
                            trial.ckpt_manager.register(Checkpoint(entry["checkpoint_path"]), metrics)
                        decision = scheduler.on_result(trial, metrics)
                        if decision == STOP:
                            break
                        if isinstance(scheduler, PopulationBasedTraining):
                            new_cfg = scheduler.maybe_exploit(trial, metrics, trials)
                            if new_cfg is not None:
                                donor = next(
                                    t for t in trials
                                    if t.trial_id == new_cfg["_pbt_exploit_from"]
                                )
                                trial.config = {k: v for k, v in new_cfg.items()
                                                if k != "_pbt_exploit_from"}
                                donor_ckpt = donor.ckpt_manager.latest if donor.ckpt_manager else None
                                trial.resume_path = donor_ckpt.path if donor_ckpt else None
                                ray.kill(trial.actor)
                                start(trial)
                                decision = CONTINUE
                                break
                    if decision == STOP:
                        trial.state = "TERMINATED"
                        ray.kill(trial.actor)
                        running.remove(trial)
                        finish(trial)
                        self._save_experiment_state(exp_dir, trials)
                    elif poll.get("error"):
                        trial.state = "ERROR"
                        trial.error = poll["error"]
                        ray.kill(trial.actor)
                        running.remove(trial)
                        finish(trial)
                        self._save_experiment_state(exp_dir, trials)
                    elif poll.get("done"):
                        trial.state = "TERMINATED"
                        ray.kill(trial.actor)
                        running.remove(trial)
                        finish(trial)
                        self._save_experiment_state(exp_dir, trials)

        finally:
            # error paths (actor-start timeout, Ctrl-C) must still
            # close logger files / flush TB writers
            callbacks.on_experiment_end(trials)
        self._save_experiment_state(exp_dir, trials)
        results = [
            Result(
                metrics=t.last_metrics,
                checkpoint=t.ckpt_manager.best if t.ckpt_manager else None,
                path=t.dir,
                error=RuntimeError(t.error) if t.error else None,
                metrics_history=t.metrics_history,
            )
            for t in trials
        ]
        return ResultGrid(results)
