"""Trial schedulers: FIFO, ASHA, PBT.

Reference: ``python/ray/tune/schedulers/`` — ``async_hyperband.py``
(ASHA), ``pbt.py`` (PopulationBasedTraining). Decisions are made on each
reported result: CONTINUE / STOP; PBT additionally mutates a trial's
config from a better trial's checkpoint at perturbation intervals.
"""

from __future__ import annotations

import random
from typing import Any

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial, metrics: dict) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    """ASHA: promote only the top 1/reduction_factor of trials past each
    rung milestone; stop the rest at the rung. Reference:
    schedulers/async_hyperband.py."""

    def __init__(
        self,
        *,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        self._metric = metric
        self._sign = 1.0 if mode == "max" else -1.0
        self._time_attr = time_attr
        self._max_t = max_t
        # rung milestones: grace_period * rf^k up to max_t
        self._rungs: list[int] = []
        t = grace_period
        while t < max_t:
            self._rungs.append(t)
            t *= reduction_factor
        self._rf = reduction_factor
        self._rung_scores: dict[int, list[float]] = {r: [] for r in self._rungs}
        self._trial_rung: dict[Any, int] = {}

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self._time_attr, 0)
        score = self._sign * float(metrics.get(self._metric, float("-inf")))
        for rung in self._rungs:
            if t >= rung and self._trial_rung.get(trial, -1) < rung:
                self._trial_rung[trial] = rung
                scores = self._rung_scores[rung]
                scores.append(score)
                if len(scores) >= 2:
                    import numpy as np

                    # promote only the top 1/rf fraction recorded so far
                    cutoff = float(np.percentile(scores, (1 - 1 / self._rf) * 100))
                    if score < cutoff:
                        return STOP
        return CONTINUE


class HyperBandScheduler:
    """Multi-bracket HyperBand (stop-based, ASHA-promotion variant):
    trials are dealt round-robin into ``s_max+1`` brackets with different
    initial budgets; within a bracket, each rung keeps the top
    1/``reduction_factor``. Brackets with small grace periods kill bad
    configs early; the conservative bracket never early-stops — the
    hedge that distinguishes HyperBand from single-bracket ASHA.
    Reference: ``tune/schedulers/hyperband.py`` (bracket structure) with
    async stop decisions (``async_hyperband.py:187`` _Bracket rungs).
    """

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration", max_t: int = 81,
                 reduction_factor: int = 3):
        import math

        self._sign = 1.0 if mode == "max" else -1.0
        self._metric = metric
        self._time_attr = time_attr
        s_max = int(math.log(max_t, reduction_factor))
        # bracket s: grace period rf^s (s = s_max is the no-early-stop one)
        self._brackets = [
            AsyncHyperBandScheduler(
                metric=metric, mode=mode, time_attr=time_attr, max_t=max_t,
                grace_period=reduction_factor ** s,
                reduction_factor=reduction_factor,
            )
            for s in range(s_max + 1)
        ]
        self._assignment: dict[Any, int] = {}
        self._next = 0

    def on_result(self, trial, metrics: dict) -> str:
        idx = self._assignment.get(trial)
        if idx is None:
            idx = self._assignment[trial] = self._next % len(self._brackets)
            self._next += 1
        return self._brackets[idx].on_result(trial, metrics)


class MedianStoppingRule:
    """Stop a trial whose best result so far is worse than the median of
    the other trials' RUNNING MEANS at the same step (reference
    ``tune/schedulers/median_stopping_rule.py``)."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 5, min_samples_required: int = 3):
        self._metric = metric
        self._sign = 1.0 if mode == "max" else -1.0
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._history: dict[Any, list[float]] = {}
        self._best: dict[Any, float] = {}

    def on_result(self, trial, metrics: dict) -> str:
        score = self._sign * float(metrics.get(self._metric, float("-inf")))
        self._history.setdefault(trial, []).append(score)
        self._best[trial] = max(self._best.get(trial, float("-inf")), score)
        t = metrics.get(self._time_attr, len(self._history[trial]))
        if t < self._grace:
            return CONTINUE
        other_means = [
            sum(h) / len(h) for tr, h in self._history.items() if tr is not trial and h
        ]
        if len(other_means) < self._min_samples:
            return CONTINUE
        other_means.sort()
        median = other_means[len(other_means) // 2]
        if self._best[trial] < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining:
    """PBT: at each perturbation interval, bottom-quantile trials exploit a
    top-quantile trial's checkpoint + config and explore by mutation.
    Reference: schedulers/pbt.py."""

    def __init__(
        self,
        *,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: dict | None = None,
        quantile_fraction: float = 0.25,
        seed: int | None = None,
    ):
        self._metric = metric
        self._sign = 1.0 if mode == "max" else -1.0
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._last_perturb: dict[Any, int] = {}
        self._scores: dict[Any, float] = {}

    def on_result(self, trial, metrics: dict) -> str:
        self._scores[trial] = self._sign * float(metrics.get(self._metric, float("-inf")))
        return CONTINUE

    def maybe_exploit(self, trial, metrics: dict, population: list) -> dict | None:
        """Returns a new (exploited+explored) config if the trial should
        restart from a better trial, else None. Controller applies it."""
        t = metrics.get(self._time_attr, 0)
        if t - self._last_perturb.get(trial, 0) < self._interval:
            return None
        self._last_perturb[trial] = t
        if len(self._scores) < 2:
            return None
        ranked = sorted(population, key=lambda tr: self._scores.get(tr, float("-inf")))
        k = max(1, int(len(ranked) * self._quantile))
        bottom, top = ranked[:k], ranked[-k:]
        if trial not in bottom:
            return None
        donor = self._rng.choice(top)
        if donor is trial:
            return None
        new_config = dict(donor.config)
        for key, mut in self._mutations.items():
            if callable(mut):
                new_config[key] = mut()
            elif isinstance(mut, list):
                new_config[key] = self._rng.choice(mut)
            else:  # numeric perturbation: x0.8 or x1.2
                base = new_config.get(key, trial.config.get(key))
                new_config[key] = base * self._rng.choice([0.8, 1.2])
        new_config["_pbt_exploit_from"] = donor.trial_id
        return new_config


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (reference ``tune/schedulers/pb2.py``,
    Parker-Holder et al. 2020): PBT's exploit step, but EXPLORE selects
    the new hyperparameters by GP-UCB over observed (config -> reward
    improvement) data instead of random multiplicative perturbation —
    markedly more sample-efficient for small populations.

    ``hyperparam_bounds``: {name: (low, high)} continuous ranges the GP
    models (categorical mutations are not supported — PBT handles those).
    """

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25,
                 seed: int | None = None,
                 ucb_beta: float = 1.5,
                 n_candidates: int = 128):
        super().__init__(metric=metric, mode=mode, time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self._bounds = hyperparam_bounds or {}
        self._beta = ucb_beta
        self._n_candidates = n_candidates
        self._prev_score: dict[Any, float] = {}
        # GP dataset: (normalized config vector, reward improvement)
        self._X: list[list[float]] = []
        self._y: list[float] = []

    # -------------------------------------------------------------- data
    def _vec(self, config: dict) -> list[float]:
        out = []
        for k, (lo, hi) in self._bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def on_result(self, trial, metrics: dict) -> str:
        score = self._sign * float(metrics.get(self._metric, float("-inf")))
        prev = self._prev_score.get(trial)
        if prev is not None and prev != float("-inf") and score != float("-inf"):
            self._X.append(self._vec(trial.config))
            self._y.append(score - prev)
        self._prev_score[trial] = score
        return super().on_result(trial, metrics)

    # ---------------------------------------------------------------- GP
    def _gp_ucb(self, donor_config: dict) -> dict:
        import numpy as np

        keys = list(self._bounds)
        cand = np.asarray(
            [[self._rng.random() for _ in keys]
             for _ in range(self._n_candidates)])
        if len(self._y) >= 3:
            X = np.asarray(self._X)
            y = np.asarray(self._y)
            y = (y - y.mean()) / (y.std() + 1e-9)
            ls = 0.3
            def k(a, b):
                d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
                return np.exp(-d2 / (2 * ls * ls))
            K = k(X, X) + 1e-3 * np.eye(len(X))
            Kinv_y = np.linalg.solve(K, y)
            Ks = k(cand, X)                        # [n_cand, n_obs]
            mu = Ks @ Kinv_y
            # diag of posterior cov
            v = np.linalg.solve(K, Ks.T)
            var = np.clip(1.0 - (Ks * v.T).sum(1), 1e-9, None)
            scores = mu + self._beta * np.sqrt(var)
            best = cand[int(np.argmax(scores))]
        else:
            best = cand[0]                         # no data yet: random
        out = dict(donor_config)
        for i, kname in enumerate(keys):
            lo, hi = self._bounds[kname]
            val = lo + float(best[i]) * (hi - lo)
            if isinstance(donor_config.get(kname), int):
                val = int(round(val))
            out[kname] = val
        return out

    def maybe_exploit(self, trial, metrics: dict, population: list) -> dict | None:
        t = metrics.get(self._time_attr, 0)
        if t - self._last_perturb.get(trial, 0) < self._interval:
            return None
        self._last_perturb[trial] = t
        if len(self._scores) < 2:
            return None
        ranked = sorted(population,
                        key=lambda tr: self._scores.get(tr, float("-inf")))
        k = max(1, int(len(ranked) * self._quantile))
        bottom, top = ranked[:k], ranked[-k:]
        if trial not in bottom:
            return None
        donor = self._rng.choice(top)
        if donor is trial:
            return None
        new_config = self._gp_ucb(donor.config)
        new_config["_pbt_exploit_from"] = donor.trial_id
        # the exploited trial restarts: its next improvement baseline resets
        self._prev_score.pop(trial, None)
        return new_config
