"""Tune callbacks and file loggers.

Equivalent of the reference's callback/logger stack —
``python/ray/tune/callback.py`` (Callback interface),
``tune/logger/json.py``, ``logger/csv.py``, ``logger/tensorboardx.py``.
Callbacks hang off ``RunConfig.callbacks`` and the TuneController calls
them at trial lifecycle points; the bundled loggers write per-trial
``result.json`` / ``progress.csv`` / TensorBoard event files into each
trial's directory, so standard dashboards point at the experiment dir
unchanged.
"""

from __future__ import annotations

import csv
import json
import numbers
import os
from typing import Any


class Callback:
    """Lifecycle hooks (subset of reference tune.Callback): override any."""

    def setup(self, **info) -> None:
        pass

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: dict) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def on_experiment_end(self, trials: list) -> None:
        pass


class CallbackList:
    """Fans every hook out to each callback; one callback's failure is
    logged, not fatal to the experiment (reference behavior)."""

    def __init__(self, callbacks: list[Callback] | None):
        self._callbacks = list(callbacks or [])

    def __bool__(self) -> bool:
        return bool(self._callbacks)

    def _fan(self, hook: str, *args, **kwargs) -> None:
        import logging

        for cb in self._callbacks:
            try:
                getattr(cb, hook)(*args, **kwargs)
            except Exception:
                logging.getLogger(__name__).exception(
                    "tune callback %s.%s failed", type(cb).__name__, hook)

    def setup(self, **info):
        self._fan("setup", **info)

    def on_trial_start(self, trial):
        self._fan("on_trial_start", trial)

    def on_trial_result(self, trial, result):
        self._fan("on_trial_result", trial, result)

    def on_trial_complete(self, trial):
        self._fan("on_trial_complete", trial)

    def on_trial_error(self, trial):
        self._fan("on_trial_error", trial)

    def on_experiment_end(self, trials):
        self._fan("on_experiment_end", trials)


class JsonLoggerCallback(Callback):
    """One JSON line per reported result: ``<trial.dir>/result.json``."""

    def __init__(self):
        self._files: dict[str, Any] = {}

    def on_trial_start(self, trial) -> None:
        if trial.trial_id in self._files:
            return  # PBT exploit restart: keep the open file
        os.makedirs(trial.dir, exist_ok=True)
        # "w": a restore re-runs the trial with reset history, so stale
        # lines from the aborted attempt must not double-count
        self._files[trial.trial_id] = open(
            os.path.join(trial.dir, "result.json"), "w")

    def on_trial_result(self, trial, result: dict) -> None:
        f = self._files.get(trial.trial_id)
        if f is None:
            return
        json.dump(result, f, default=str)
        f.write("\n")
        f.flush()

    def _close(self, trial) -> None:
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()

    on_trial_complete = _close
    on_trial_error = _close

    def on_experiment_end(self, trials) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()


class CSVLoggerCallback(Callback):
    """``<trial.dir>/progress.csv`` — header from the FIRST result; later
    keys outside it are dropped (the reference's CSV logger contract)."""

    def __init__(self):
        self._writers: dict[str, tuple[Any, Any, list[str]]] = {}

    def on_trial_start(self, trial) -> None:
        os.makedirs(trial.dir, exist_ok=True)

    def on_trial_result(self, trial, result: dict) -> None:
        entry = self._writers.get(trial.trial_id)
        if entry is None:
            # "w": restore re-runs reset trials; appending would write a
            # second header mid-file (in-process PBT restarts reuse the
            # live writer entry, so nothing is lost there)
            f = open(os.path.join(trial.dir, "progress.csv"), "w", newline="")
            fields = list(result.keys())
            w = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            w.writeheader()
            self._writers[trial.trial_id] = entry = (f, w, fields)
        f, w, _fields = entry
        w.writerow({k: result.get(k) for k in _fields})
        f.flush()

    def _close(self, trial) -> None:
        entry = self._writers.pop(trial.trial_id, None)
        if entry is not None:
            entry[0].close()

    on_trial_complete = _close
    on_trial_error = _close

    def on_experiment_end(self, trials) -> None:
        for f, _w, _f2 in self._writers.values():
            f.close()
        self._writers.clear()


class TBXLoggerCallback(Callback):
    """TensorBoard event files per trial (scalar metrics only), via
    ``torch.utils.tensorboard`` (present in this image; the reference
    uses tensorboardX)."""

    def __init__(self):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception as e:  # pragma: no cover - env without torch tb
            raise ImportError(
                "TBXLoggerCallback needs torch.utils.tensorboard "
                f"(unavailable: {e})") from e
        self._writer_cls = SummaryWriter
        self._writers: dict[str, Any] = {}
        self._steps: dict[str, int] = {}

    def on_trial_start(self, trial) -> None:
        if trial.trial_id in self._writers:
            return  # PBT exploit restart: keep writer and step counter
        self._writers[trial.trial_id] = self._writer_cls(log_dir=trial.dir)
        self._steps[trial.trial_id] = 0

    def on_trial_result(self, trial, result: dict) -> None:
        w = self._writers.get(trial.trial_id)
        if w is None:
            return
        step = int(result.get("training_iteration",
                              self._steps[trial.trial_id]))
        self._steps[trial.trial_id] += 1
        for k, v in result.items():
            if isinstance(v, numbers.Number) and not isinstance(v, bool):
                w.add_scalar(k, float(v), global_step=step)
        w.flush()

    def _close(self, trial) -> None:
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()
        self._steps.pop(trial.trial_id, None)

    on_trial_complete = _close
    on_trial_error = _close

    def on_experiment_end(self, trials) -> None:
        for w in self._writers.values():
            w.close()
        self._writers.clear()


DEFAULT_LOGGERS = (JsonLoggerCallback, CSVLoggerCallback)
