"""Compiled-loop dispatch suite (ROADMAP item 4).

Measures what the persistent compiled-loop runtime (``dag/loop.py``)
exists to kill: the per-tick dynamic dispatch cost of steady-state
iteration, and its effect on the pipeline-parallel engine tick path.

Two phases, guarded by ``ray_tpu.bench_check``:

  * **Tick dispatch overhead** — a 2-stage trivial actor pipeline driven
    (a) dynamically (one ``.remote()`` chain + ``get`` per tick, the
    submit→lease→push path every iteration) and (b) through a compiled
    loop (channel write + read per tick, zero task submission).

      - ``dag_tick_dispatch_overhead_dynamic_us`` — dynamic per-tick µs
      - ``dag_tick_dispatch_overhead_us``         — compiled per-tick µs
      - ``dag_loop_ticks_per_s``                  — compiled PIPELINED
        tick rate (puts streamed ``credits`` deep, gets drained behind)

  * **pp decode tok/s** — the debug-model engine over a 1-host sharded
    executor with a pp=2 mesh, decoding the same workload through the
    dynamic per-burst RPC path and the compiled loop:

      - ``pp_decode_tok_s_dynamic`` / ``pp_decode_tok_s_compiled``

    On hosts whose jax cannot run the pp shard_map programs (< 2
    devices, or no ``jax.shard_map``) the phase records
    ``pp_decode_*_skipped`` markers instead — ``bench_check`` treats the
    absence as intentional, never as a silent regression.

Sizes are env-tunable (``RAY_TPU_DAG_BENCH_{TICKS,DECODE_BURSTS}``). Run
standalone via ``python -m ray_tpu.cli bench dag`` or as part of
``bench.py``.
"""

from __future__ import annotations

import os
import sys
import time


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _bench_tick_overhead(out: dict, ticks: int) -> None:
    import ray_tpu
    from ray_tpu.dag import InputNode, compile_loop

    @ray_tpu.remote
    class _Stage:
        def f(self, x):
            return x + 1

    a, b = _Stage.remote(), _Stage.remote()
    # Warm both actors (worker spawn + first-call export are not
    # dispatch overhead).
    ray_tpu.get([a.f.remote(0), b.f.remote(0)], timeout=120)

    # Dynamic: the per-tick task path — one submit→lease→push→return
    # chain per stage per tick, refs threading stage to stage.
    t0 = time.perf_counter()
    for i in range(ticks):
        assert ray_tpu.get(b.f.remote(a.f.remote(i)), timeout=120) == i + 2
    dyn_s = time.perf_counter() - t0
    out["dag_tick_dispatch_overhead_dynamic_us"] = round(
        dyn_s / ticks * 1e6, 1)

    with InputNode() as inp:
        dag = b.f.bind(a.f.bind(inp))
    loop = compile_loop(dag)
    try:
        assert loop.run(0) == 2  # warm the resident executors
        # Compiled, synchronous: one full channel round trip per tick —
        # the steady-state dispatch cost with zero task submission.
        t0 = time.perf_counter()
        for i in range(ticks):
            assert loop.run(i) == i + 2
        comp_s = time.perf_counter() - t0
        out["dag_tick_dispatch_overhead_us"] = round(comp_s / ticks * 1e6, 1)
        # Compiled, pipelined: puts stream ahead of gets (credits deep) —
        # the sustained tick rate of a busy loop.
        t0 = time.perf_counter()
        done = 0
        for i in range(ticks):
            loop.put(i)
            while loop.in_flight >= loop.credits:
                loop.get()
                done += 1
        while done < ticks:
            loop.get()
            done += 1
        out["dag_loop_ticks_per_s"] = round(
            ticks / (time.perf_counter() - t0), 1)
    finally:
        loop.teardown()
    out["dag_bench_ticks_cfg"] = ticks


def _bench_obs_overhead(out: dict, ticks: int) -> None:
    """Stall-recorder cost guard: the same 2-stage compiled loop timed
    with the per-tick stall recorder ON (the always-on default) vs OFF.

    Two estimates, one guard:

      - ``loop_obs_tick_{recording,baseline}_us`` — end-to-end A/B
        floors: both loops co-exist (an idle stage parks in a 1ms
        backoff poll) and short batches alternate between them, min
        over rounds. Honesty note: on a shared CPU sandbox the
        per-instance placement variance (±10%) exceeds the recorder's
        true cost (~2µs on a ~350µs tick), so the difference of these
        two cells carries that noise — they are REPORTED, not guarded.
      - ``loop_obs_overhead_frac`` — the GUARDED cell (PERF gate
        ≤ 0.02): the recorder's exact in-path ops (ring.record + the
        amortized span-cadence histogram flush + the time-gated
        snapshot-file write share) measured directly, over the measured
        tick-dispatch floor. The ops are pure in-process CPU, so the
        direct measurement is the same work the stage executor pays,
        without the channel round-trip noise.
      - ``dag_loop_stall_{wait_up,compute,wait_down}_frac`` — the
        recording loop's bottleneck-stage stall split (driver-visible
        proof the attribution pipeline works end to end)
    """
    import ray_tpu
    from ray_tpu.core.config import get_config
    from ray_tpu.dag import InputNode, compile_loop

    @ray_tpu.remote
    class _Stage:
        def f(self, x):
            return x + 1

    cfg = get_config()
    saved = cfg.dag_loop_stall_recording

    def build(recording: bool):
        # Fresh actors per mode: a resident tick executor parks its
        # actor's only thread, so loops can't share stage actors.
        cfg.dag_loop_stall_recording = recording
        a, b = _Stage.remote(), _Stage.remote()
        ray_tpu.get([a.f.remote(0), b.f.remote(0)], timeout=120)
        with InputNode() as inp:
            dag = b.f.bind(a.f.bind(inp))
        loop = compile_loop(dag)
        assert loop.run(0) == 2  # warm the resident executors
        return loop

    def batch(loop, n: int) -> float:
        t0 = time.perf_counter()
        for i in range(n):
            loop.run(i)
        return (time.perf_counter() - t0) / n

    rounds, per_batch = 24, max(20, ticks // 5)
    loops = {}
    try:
        loops["on"], loops["off"] = build(True), build(False)
        floors = {"on": None, "off": None}
        for r in range(rounds):
            for mode in (("on", "off") if r % 2 == 0 else ("off", "on")):
                dt = batch(loops[mode], per_batch)
                if floors[mode] is None or dt < floors[mode]:
                    floors[mode] = dt
        stats = loops["on"].stats(fallback_gcs=False)
    finally:
        cfg.dag_loop_stall_recording = saved
        for loop in loops.values():
            loop.teardown()
    on_s, off_s = floors["on"], floors["off"]
    out["loop_obs_tick_recording_us"] = round(on_s * 1e6, 2)
    out["loop_obs_tick_baseline_us"] = round(off_s * 1e6, 2)
    out["loop_obs_overhead_frac"] = round(
        _recorder_cost_s(cfg) / min(on_s, off_s), 4)
    bn = (stats or {}).get("bottleneck")
    if bn:
        frac = ((stats.get("stages") or {}).get(bn) or {}).get("frac") or {}
        for bucket in ("wait_up", "compute", "wait_down"):
            out[f"dag_loop_stall_{bucket}_frac"] = frac.get(bucket, 0.0)


def _recorder_cost_s(cfg) -> float:
    """Per-tick cost of the stall recorder's in-path work, measured
    directly: ``ring.record`` every tick, the bulk histogram flush every
    ``dag_loop_span_every`` ticks, and the snapshot-file write's
    time-gated share (one ~0.5ms write per ``_STALL_FILE_MIN_S``)."""
    import json
    import os
    import shutil
    import tempfile

    from ray_tpu.dag.loop import _STALL_FILE_MIN_S
    from ray_tpu.observability import loop_recorder
    from ray_tpu.util.metrics import Histogram

    ring = loop_recorder.StallRing(
        int(getattr(cfg, "dag_loop_stall_ring", 256)))
    hist = Histogram("loop_obs_bench_tick_ms",
                     boundaries=loop_recorder.TICK_MS_BOUNDARIES,
                     tag_keys=("loop", "stage", "bucket"), register=False)
    tags = tuple({"loop": "bench", "stage": "f", "bucket": b}
                 for b in loop_recorder.STALL_BUCKETS)
    flush_every = int(getattr(cfg, "dag_loop_span_every", 64) or 64)
    n = max(4000, 24 * flush_every)
    t0 = time.perf_counter()
    for k in range(1, n + 1):
        ring.record(0.05, 0.2, 0.01)
        if k % flush_every == 0:
            rows = ring.drain()
            hist.observe_many([r[0] for r in rows], tags=tags[0])
            hist.observe_many([r[1] for r in rows], tags=tags[1])
            hist.observe_many([r[2] for r in rows], tags=tags[2])
    per_tick_s = (time.perf_counter() - t0) / n

    d = tempfile.mkdtemp(prefix="loop_obs_bench_")
    try:
        path, snap = os.path.join(d, "stall.json"), ring.snapshot()
        t0 = time.perf_counter()
        for _ in range(8):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, path)
        write_s = (time.perf_counter() - t0) / 8
    finally:
        shutil.rmtree(d, ignore_errors=True)
    # one gated write per _STALL_FILE_MIN_S, spread over the ticks that
    # fit in that window (conservatively at a fast 100µs tick)
    return per_tick_s + write_s / (_STALL_FILE_MIN_S / 100e-6)


def _bench_pp_decode(out: dict, bursts: int) -> None:
    """Debug-model pp=2 decode through the sharded engine, dynamic vs
    compiled loop. Records skip markers when the host can't run pp."""
    import jax

    if not hasattr(jax, "shard_map"):
        raise RuntimeError("jax.shard_map unavailable (needs jax >= 0.6)")

    from ray_tpu.llm import InferenceEngine, create_sharded_executor
    from ray_tpu.llm.engine import Request

    max_slots, max_len, page_size = 4, 128, 16

    def run(use_loop: bool) -> tuple[float, int]:
        executor = create_sharded_executor(
            "debug", 1,
            max_slots=max_slots,
            num_pages=InferenceEngine.total_pages(max_slots, max_len,
                                                  page_size),
            page_size=page_size,
            pp=2,
            seed=0,
            use_compiled_loop=use_loop,
        )
        try:
            eng = InferenceEngine(
                "debug", max_slots=max_slots, max_len=max_len,
                page_size=page_size, executor=executor, seed=0)
            budget = bursts * eng.decode_steps_per_dispatch
            reqs = [Request(f"r{i}", [7, 3, 5, 9][: i + 1] * 2,
                            max_new_tokens=budget + 8)
                    for i in range(max_slots)]
            for r in reqs:
                eng.add_request(r)
            # Drain admission + prefill + first-token flush so the timed
            # window is pure steady-state decode ticks.
            while not eng._active or eng._prefilling or eng._pending_first:
                eng.step()
            t0 = time.perf_counter()
            tokens = 0
            for _ in range(bursts):
                tokens += len(eng.step())
            dt = time.perf_counter() - t0
            return dt, tokens
        finally:
            executor.shutdown()

    dyn_s, dyn_tok = run(False)
    comp_s, comp_tok = run(True)
    out["pp_decode_tok_s_dynamic"] = round(dyn_tok / dyn_s, 1)
    out["pp_decode_tok_s_compiled"] = round(comp_tok / comp_s, 1)
    out["dag_bench_decode_bursts_cfg"] = bursts


def run_dag_bench(*, ticks: int | None = None, bursts: int | None = None,
                  connect: bool = True) -> dict:
    """Run both phases and return the metrics dict. With ``connect``
    (default) a local cluster is started and shut down; pass False to
    run inside an already-initialized driver."""
    import ray_tpu

    ticks = ticks or _env_int("RAY_TPU_DAG_BENCH_TICKS", 300)
    bursts = bursts or _env_int("RAY_TPU_DAG_BENCH_DECODE_BURSTS", 12)
    out: dict = {}
    if connect:
        ray_tpu.init(num_cpus=max(8, os.cpu_count() or 8),
                     ignore_reinit_error=True)
    try:
        _bench_tick_overhead(out, ticks)
        _bench_obs_overhead(out, ticks)
        try:
            _bench_pp_decode(out, bursts)
        except Exception as e:
            # Intentional skip on env gaps (bench_check honors the
            # markers); the real pp numbers come from the chip box.
            print(f"dag bench: pp decode phase skipped: {e}",
                  file=sys.stderr)
            out["pp_decode_skip_reason"] = f"{type(e).__name__}: {e}"
            out["pp_decode_tok_s_dynamic_skipped"] = True
            out["pp_decode_tok_s_compiled_skipped"] = True
    finally:
        if connect:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass
    return out


if __name__ == "__main__":
    import json

    print(json.dumps(run_dag_bench(), indent=2))
