"""Grafana dashboard factory.

Equivalent of the reference's generated Grafana dashboards
(``python/ray/dashboard/modules/metrics/grafana_dashboard_factory.py`` /
``dashboards/default_dashboard_panels.py``): emits a provisioning-ready
dashboard JSON over the Prometheus metrics this framework exports
(``ray_tpu.util.metrics.prometheus_text`` — framework gauges prefixed
``ray_tpu_`` plus user Counters/Gauges/Histograms).

Usage::

    python -m ray_tpu.grafana > ray_tpu_dashboard.json
    # then import in Grafana, or drop into provisioning/dashboards/

The datasource is templated (``${datasource}``) so the same JSON works
against any Prometheus instance.
"""

from __future__ import annotations

import json

_DS = {"type": "prometheus", "uid": "${datasource}"}

# Chart colors follow the validated default palette (one hue per series
# slot, fixed order — see the data-viz method): blue, orange, aqua, yellow.
_SLOT_COLORS = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100"]


def _panel(panel_id: int, title: str, targets: list[dict], *, grid: dict,
           unit: str = "short", kind: str = "timeseries") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": kind,
        "datasource": _DS,
        "gridPos": grid,
        "fieldConfig": {
            "defaults": {
                "unit": unit,
                "custom": {
                    "lineWidth": 2,
                    "fillOpacity": 0,
                    "showPoints": "never",
                    "drawStyle": "line",
                },
                "color": {"mode": "palette-classic"},
            },
            "overrides": [
                {
                    "matcher": {"id": "byFrameRefID", "options": chr(ord("A") + i)},
                    "properties": [{
                        "id": "color",
                        "value": {"mode": "fixed", "fixedColor": _SLOT_COLORS[i % len(_SLOT_COLORS)]},
                    }],
                }
                for i in range(len(targets))
            ],
        },
        "targets": [
            {"refId": chr(ord("A") + i), "expr": t["expr"],
             "legendFormat": t.get("legend", "__auto"), "datasource": _DS}
            for i, t in enumerate(targets)
        ],
        "options": {
            "legend": {"displayMode": "list", "placement": "bottom",
                       # A templated legend ({{label}}) fans one target out
                       # into many series — those need the legend too.
                       "showLegend": len(targets) > 1
                       or "{{" in targets[0].get("legend", "")},
            "tooltip": {"mode": "multi", "sort": "desc"},
        },
    }


def _stat(panel_id: int, title: str, expr: str, *, grid: dict,
          unit: str = "short") -> dict:
    p = _panel(panel_id, title, [{"expr": expr}], grid=grid, unit=unit, kind="stat")
    p["options"] = {"reduceOptions": {"calcs": ["lastNotNull"]},
                    "colorMode": "none", "graphMode": "area"}
    return p


def generate_dashboard(title: str = "ray_tpu cluster") -> dict:
    """The default cluster dashboard: nodes / resources / tasks / actors /
    object store / serve, one row each (reference
    ``default_dashboard_panels.py`` panel inventory, TPU-scoped)."""
    W, H = 8, 7  # grid units per panel
    panels = [
        # Row 1: headline stats
        _stat(1, "Nodes alive", 'ray_tpu_nodes{state="ALIVE"}',
              grid={"x": 0, "y": 0, "w": 4, "h": 4}),
        _stat(2, "Actors alive", 'ray_tpu_actors{state="ALIVE"}',
              grid={"x": 4, "y": 0, "w": 4, "h": 4}),
        _stat(3, "Tasks running", 'ray_tpu_tasks{state="RUNNING"}',
              grid={"x": 8, "y": 0, "w": 4, "h": 4}),
        _stat(4, "TPU chips in use",
              "ray_tpu_resource_used{resource=\"TPU\"}",
              grid={"x": 12, "y": 0, "w": 4, "h": 4}),
        _stat(5, "Object store used",
              "sum(ray_tpu_object_store_used_bytes)",
              grid={"x": 16, "y": 0, "w": 4, "h": 4}, unit="bytes"),
        _stat(6, "Placement groups", 'ray_tpu_placement_groups{state="CREATED"}',
              grid={"x": 20, "y": 0, "w": 4, "h": 4}),
        # Row 2: utilization over time
        _panel(10, "CPU utilization", [
            {"expr": 'ray_tpu_resource_used{resource="CPU"}', "legend": "used"},
            {"expr": 'ray_tpu_resource_total{resource="CPU"}', "legend": "total"},
        ], grid={"x": 0, "y": 4, "w": W, "h": H}),
        _panel(11, "TPU utilization", [
            {"expr": 'ray_tpu_resource_used{resource="TPU"}', "legend": "used"},
            {"expr": 'ray_tpu_resource_total{resource="TPU"}', "legend": "total"},
        ], grid={"x": W, "y": 4, "w": W, "h": H}),
        _panel(12, "Object store bytes by node", [
            {"expr": "ray_tpu_object_store_used_bytes", "legend": "{{node_id}} used"},
        ], grid={"x": 2 * W, "y": 4, "w": W, "h": H}, unit="bytes"),
        # Row 3: scheduler / control plane
        _panel(20, "Tasks by state", [
            {"expr": "ray_tpu_tasks", "legend": "{{state}}"},
        ], grid={"x": 0, "y": 4 + H, "w": W, "h": H}),
        _panel(21, "Actors by state", [
            {"expr": "ray_tpu_actors", "legend": "{{state}}"},
        ], grid={"x": W, "y": 4 + H, "w": W, "h": H}),
        _panel(22, "Pending resource demand", [
            {"expr": "ray_tpu_pending_demand", "legend": "{{shape}}"},
        ], grid={"x": 2 * W, "y": 4 + H, "w": W, "h": H}),
        # Row 4: spill + serve
        _panel(30, "Spill / restore throughput", [
            {"expr": "rate(ray_tpu_spill_bytes_total[5m])", "legend": "spilled"},
            {"expr": "rate(ray_tpu_restore_bytes_total[5m])", "legend": "restored"},
        ], grid={"x": 0, "y": 4 + 2 * H, "w": W, "h": H}, unit="Bps"),
        _panel(31, "Serve requests", [
            {"expr": "rate(serve_num_requests_total[1m])", "legend": "{{deployment}}"},
        ], grid={"x": W, "y": 4 + 2 * H, "w": W, "h": H}, unit="reqps"),
        _panel(32, "Serve latency p50", [
            {"expr": "histogram_quantile(0.5, rate(serve_request_latency_ms_bucket[5m]))",
             "legend": "{{deployment}}"},
        ], grid={"x": 2 * W, "y": 4 + 2 * H, "w": W, "h": H}, unit="ms"),
        # Row 5: request-path observability (tracing PR): engine TTFT,
        # router queue wait, and the raylet lease pipeline stages.
        _panel(40, "Serve TTFT p50 / p95", [
            {"expr": "histogram_quantile(0.5, rate(serve_ttft_ms_bucket[5m]))",
             "legend": "p50 {{deployment}}"},
            {"expr": "histogram_quantile(0.95, rate(serve_ttft_ms_bucket[5m]))",
             "legend": "p95 {{deployment}}"},
        ], grid={"x": 0, "y": 4 + 3 * H, "w": W, "h": H}, unit="ms"),
        _panel(41, "Serve router queue wait p95", [
            {"expr": "histogram_quantile(0.95, rate(serve_queue_wait_ms_bucket[5m]))",
             "legend": "{{deployment}}"},
        ], grid={"x": W, "y": 4 + 3 * H, "w": W, "h": H}, unit="ms"),
        _panel(42, "Lease pipeline stage p95", [
            {"expr": "histogram_quantile(0.95, sum by (le, stage) "
                     "(rate(ray_tpu_lease_stage_ms_bucket[5m])))",
             "legend": "{{stage}}"},
        ], grid={"x": 2 * W, "y": 4 + 3 * H, "w": W, "h": H}, unit="ms"),
        # SLO-serving row: the prefix-cache gauge explains TTFT moves
        # (a hit-rate drop = cold prompts = slower prefill), and the
        # per-deployment TTFT p95 is the latency_slo autoscaler's own
        # trigger signal — the panel shows exactly what it reacts to.
        _panel(45, "Prefix cache hit rate", [
            {"expr": "serve_prefix_cache_hit_rate",
             "legend": "{{deployment}}"},
        ], grid={"x": 2 * W, "y": 4 + 4 * H, "w": W, "h": H},
            unit="percentunit"),
        # Chaos injections live NEXT TO the lease-stage / leak panels: a
        # spike here explains spikes there (injected pain vs real pain).
        _panel(43, "Chaos injections by kind", [
            {"expr": "sum by (kind) "
                     "(rate(ray_tpu_chaos_injections_total[5m]))",
             "legend": "{{kind}}"},
        ], grid={"x": 0, "y": 4 + 5 * H, "w": W, "h": H}, unit="ops"),
        _panel(44, "Chaos injections by RPC method", [
            {"expr": "sum by (method) "
                     "(rate(ray_tpu_chaos_injections_total[5m]))",
             "legend": "{{method}}"},
        ], grid={"x": W, "y": 4 + 5 * H, "w": W, "h": H}, unit="ops"),
        # Compiled-loop steady state (dag/loop.py): tick rate per stage
        # proves the zero-RPC path is doing the work; ring occupancy at
        # its credit ceiling pinpoints the backpressuring stage.
        _panel(46, "Compiled-loop ticks by stage", [
            {"expr": "sum by (stage) "
                     "(rate(ray_tpu_dag_loop_ticks_total[1m]))",
             "legend": "{{stage}}"},
        ], grid={"x": 2 * W, "y": 4 + 5 * H, "w": W, "h": H}, unit="ops"),
        _panel(47, "Compiled-loop channel occupancy", [
            {"expr": "ray_tpu_dag_loop_channel_occupancy",
             "legend": "{{stage}}"},
        ], grid={"x": 2 * W, "y": 4 + 6 * H, "w": W, "h": H}),
        # Tick stall attribution (observability PR): where each resident
        # stage's tick time goes — waiting on upstream input, computing,
        # or waiting on downstream credits. A stage whose wait_down p95
        # tracks another stage's compute p95 IS being backpressured by
        # it; the p95 split names the bottleneck without a profiler.
        _panel(48, "Loop tick stall split p95 (wait_up/compute/wait_down)", [
            {"expr": "histogram_quantile(0.95, sum by (le, stage, bucket) "
                     "(rate(ray_tpu_dag_loop_tick_ms_bucket[5m])))",
             "legend": "{{stage}} {{bucket}}"},
        ], grid={"x": 0, "y": 4 + 6 * H, "w": W, "h": H}, unit="ms"),
        # Per-tenant SLO burn (flight-recorder PR): the fraction of each
        # tenant's recent requests breaching its TTFT SLO — the same
        # number serve.status() shows and breach timeline dumps key off.
        _panel(49, "Tenant SLO burn rate", [
            {"expr": "tenant_slo_burn_frac",
             "legend": "{{deployment}}/{{tenant}}"},
        ], grid={"x": W, "y": 4 + 6 * H, "w": W, "h": H},
            unit="percentunit"),
        # Row 6: memory observability (memory PR): per-node object-store
        # usage vs capacity/pinned, HBM used vs limit, worker RSS, and the
        # spill-rate-by-node view that pairs with the leak watcher.
        _panel(50, "Object store used / pinned / capacity", [
            {"expr": "ray_tpu_object_store_used_bytes", "legend": "{{node_id}} used"},
            {"expr": "ray_tpu_object_store_pinned_bytes", "legend": "{{node_id}} pinned"},
            {"expr": "ray_tpu_object_store_capacity_bytes", "legend": "{{node_id}} capacity"},
        ], grid={"x": 0, "y": 4 + 4 * H, "w": W, "h": H}, unit="bytes"),
        _panel(51, "HBM used / limit by node", [
            {"expr": "ray_tpu_hbm_used_bytes", "legend": "{{node_id}} used"},
            {"expr": "ray_tpu_hbm_peak_bytes", "legend": "{{node_id}} peak"},
            {"expr": "ray_tpu_hbm_limit_bytes", "legend": "{{node_id}} limit"},
        ], grid={"x": W, "y": 4 + 4 * H, "w": W, "h": H}, unit="bytes"),
        _panel(52, "Worker RSS / spill rate by node", [
            {"expr": "ray_tpu_worker_rss_bytes", "legend": "{{node_id}} rss"},
            {"expr": "rate(ray_tpu_spill_bytes_total[5m])",
             "legend": "{{node_id}} spill Bps"},
        ], grid={"x": 2 * W, "y": 4 + 4 * H, "w": W, "h": H}, unit="bytes"),
    ]
    return {
        "title": title,
        "uid": "ray-tpu-default",
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "editable": True,
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource", "query": "prometheus",
            "label": "Data source",
        }]},
        "panels": panels,
    }


def main() -> None:
    print(json.dumps(generate_dashboard(), indent=2))


if __name__ == "__main__":
    main()
