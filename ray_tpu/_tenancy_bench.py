"""Multi-tenant LoRA multiplexing bench: SLO isolation + mixed decode.

ISSUE 16 acceptance cells, runnable standalone (``python -m ray_tpu.cli
bench tenancy``) or inside ``bench.py``:

  * ``tenant_quiet_p95_ttft_ms_solo`` / ``_noisy`` — a quiet tenant's
    client TTFT p95 alone vs while a noisy tenant storms the SAME
    deployment at far beyond capacity. The noisy tenant carries a token
    quota (the SLO-enforcement mechanism under test): its storm is
    quota-shed to a bounded admitted stream, so the quiet p95 must move
    ≤ 15%.
  * ``tenant_goodput_frac_hot`` / ``_cold`` — per-tenant goodput under
    a mixed 2× open-loop storm where the "hot" tenant's adapter is
    HBM-resident and the "cold" tenant's adapter must hot-load through
    the replica's adapter LRU mid-storm.
  * ``tenant_mixed_batch_parity`` — 1.0 iff a decode batch mixing
    DISTINCT adapters returns byte-identical greedy tokens to serving
    the same requests sequentially.
  * ``tenant_mixed_dispatch_parity`` — 1.0 iff the mixed-adapter batch
    consumed exactly as many ``decode_dispatches`` as a same-shape
    single-adapter batch (decode cost must not scale with the number of
    distinct adapters: one dispatch carries the whole mix).
  * ``adapter_hot_load_ms`` — mean filesystem-read + device-scatter
    time to hot-load one adapter into the stack.

CPU-sandbox honest: debug presets, byte tokenizer, quotas fixed in
absolute tokens/s (no machine-speed calibration creep). Set
``RAY_TPU_BENCH_SKIP_TENANCY=1`` to leave ``*_skipped`` markers that
``bench_check`` honors.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import urllib.error
import urllib.request

SKIP_MARKERS = {
    "tenant_quiet_p95_ttft_ms_skipped": True,
    "tenant_goodput_frac_skipped": True,
    "tenant_mixed_batch_parity_skipped": True,
    "tenant_mixed_dispatch_parity_skipped": True,
    "adapter_hot_load_ms_skipped": True,
}


def _pct(sorted_vals: list[float], q: float) -> float:
    return sorted_vals[max(0, int(len(sorted_vals) * q) - 1)]


def _rand_adapter(cfg, rng, rank: int = 2, scale: float = 0.5) -> dict:
    """Random rank-``rank`` adapter arrays for every attention proj."""
    import numpy as np

    L, E, H, KH, D = (cfg.n_layers, cfg.hidden, cfg.n_heads,
                      cfg.n_kv_heads, cfg.head_dim)
    dims = {"wq": (E, H * D), "wk": (E, KH * D), "wv": (E, KH * D),
            "wo": (H * D, E)}
    out = {}
    for p, (ein, eout) in dims.items():
        out[f"{p}.A"] = (rng.standard_normal((L, ein, rank))
                         * scale / ein ** 0.5).astype(np.float32)
        out[f"{p}.B"] = (rng.standard_normal((L, rank, eout))
                         * scale).astype(np.float32)
    return out


def _engine_cells(out: dict) -> None:
    """Mixed-adapter decode cells straight off the engine: greedy byte
    parity vs sequential, dispatch-count parity vs a single-adapter
    batch of the same shape, and the adapter hot-load time."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm.engine import InferenceEngine, Request
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter
    from ray_tpu.models.llama import PRESETS, init_params

    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(16)
    lora_dir = tempfile.mkdtemp(prefix="raytpu_tenancy_eng_")
    adapters = ("t1", "t2", "t3")
    for name in adapters:
        save_adapter(os.path.join(lora_dir, f"{name}.npz"),
                     _rand_adapter(cfg, rng))
    lora = LoRAServingConfig(max_loras=4, max_rank=4,
                             dynamic_lora_loading_path=lora_dir)
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8, 2, 8],
               [1, 6, 1, 8, 0, 3, 3, 9, 8, 8], [5, 5, 5, 9, 7]]
    # Parity batch: one base + three DISTINCT adapters decode together.
    # The dispatch-count comparison uses all-adapter batches of the same
    # shape (mixed vs uniform) so plan selection is identical and the
    # ONLY variable is how many distinct adapters the batch carries.
    parity_models = [None, "t1", "t2", "t3"]
    mixed_models = ["t1", "t2", "t3", "t1"]
    single_models = ["t1", "t1", "t1", "t1"]

    def run(models, concurrent: bool):
        eng = InferenceEngine(cfg, params, max_slots=4, max_len=64,
                              lora_config=lora, enable_prefix_cache=False)
        reqs = [Request(f"r{i}", p, max_new_tokens=8, model=m)
                for i, (p, m) in enumerate(zip(prompts, models))]
        d0 = eng.metrics["decode_dispatches"]
        if concurrent:
            for r in reqs:
                eng.add_request(r)
            while any(not r.done for r in reqs):
                eng.step()
        else:
            for r in reqs:
                eng.add_request(r)
                while not r.done:
                    eng.step()
        loads = eng.lora_manager.stats() if eng.lora_manager else {}
        return ([list(r.generated) for r in reqs],
                eng.metrics["decode_dispatches"] - d0, loads)

    parity_toks, _, load_stats = run(parity_models, concurrent=True)
    seq_toks, _, _ = run(parity_models, concurrent=False)
    _, mixed_d, _ = run(mixed_models, concurrent=True)
    _, single_d, _ = run(single_models, concurrent=True)
    out["tenant_mixed_batch_parity"] = (
        1.0 if parity_toks == seq_toks else 0.0)
    out["tenant_mixed_dispatch_parity"] = (
        1.0 if mixed_d == single_d else 0.0)
    out["tenant_mixed_decode_dispatches_cfg"] = mixed_d
    out["tenant_single_decode_dispatches_cfg"] = single_d
    if load_stats.get("avg_load_ms"):
        out["adapter_hot_load_ms"] = round(load_stats["avg_load_ms"], 2)


def _one_request(addr: str, route: str, prompt: str, max_tokens: int,
                 model: str | None, tenant_header: str | None,
                 client_timeout: float) -> dict:
    """One streaming completion carrying the tenant routing key (JSON
    ``model`` field or ``x-raytpu-model`` header); returns {"status",
    "ttft_s", "wall_s", "text", "finish", "retry_after"}."""
    body: dict = {"prompt": prompt, "max_tokens": max_tokens,
                  "stream": True}
    if model:
        body["model"] = model
    headers = {"Content-Type": "application/json"}
    if tenant_header:
        headers["x-raytpu-model"] = tenant_header
    req = urllib.request.Request(addr + route + "/v1/completions",
                                 data=json.dumps(body).encode(),
                                 headers=headers)
    t0 = time.perf_counter()
    out = {"status": "200", "ttft_s": None, "wall_s": None, "text": "",
           "finish": "", "retry_after": None}
    try:
        with urllib.request.urlopen(req, timeout=client_timeout) as resp:
            for line in resp:
                line = line.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                choice = json.loads(line[6:])["choices"][0]
                if out["ttft_s"] is None and choice.get("text"):
                    out["ttft_s"] = time.perf_counter() - t0
                out["text"] += choice.get("text", "")
                if choice.get("finish_reason"):
                    out["finish"] = choice["finish_reason"]
    except urllib.error.HTTPError as e:
        out["status"] = str(e.code)
        out["retry_after"] = e.headers.get("Retry-After")
        try:
            e.read()
        except Exception:
            pass
    except Exception as e:
        out["status"] = type(e).__name__
    out["wall_s"] = time.perf_counter() - t0
    return out


def run_tenancy_bench(storm_s: float | None = None) -> dict:
    if os.environ.get("RAY_TPU_BENCH_SKIP_TENANCY") == "1":
        return dict(SKIP_MARKERS)
    out: dict = {}
    _engine_cells(out)

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app
    from ray_tpu.llm.lora import save_adapter
    from ray_tpu.models.llama import PRESETS

    import numpy as np

    preset = os.environ.get("RAY_TPU_TENANCY_PRESET", "debug-128")
    storm_s = storm_s or float(os.environ.get("RAY_TPU_TENANCY_STORM_S", "6"))
    max_tokens = 8
    max_slots = 4
    quiet_n = 10

    lora_dir = tempfile.mkdtemp(prefix="raytpu_tenancy_")
    rng = np.random.default_rng(7)
    for name in ("noisy", "hot", "cold"):
        save_adapter(os.path.join(lora_dir, f"{name}.npz"),
                     _rand_adapter(PRESETS[preset], rng))

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    # The noisy tenant's quota is the isolation mechanism: fixed in
    # ABSOLUTE tokens/s (~half a request per second at 48 tokens each),
    # far below any machine's capacity, so the storm is quota-shed to a
    # trickle no matter how fast or slow the sandbox is.
    tenancy_config = {
        "max_loaded_adapters": 2,
        "tenants": {
            "quiet": {"weight": 2.0},
            "noisy": {"weight": 1.0, "tokens_per_s": 24.0,
                      "burst_tokens": 96.0},
            "hot": {"weight": 1.0},
            "cold": {"weight": 1.0},
        },
    }
    serve.run(
        build_llm_app(
            preset, max_slots=max_slots, max_len=128, page_size=16,
            prefill_chunk_size=64, num_replicas=1,
            max_ongoing_requests=max_slots, max_queued_requests=8,
            lora_config={"max_loras": 4, "max_rank": 4,
                         "dynamic_lora_loading_path": lora_dir},
            tenancy_config=tenancy_config),
        name="tenancy", route_prefix="/mt", timeout_s=360.0)
    addr = serve.http_address()
    route = "/mt"
    # The router queue bound lives in the PROXY process: tune it through
    # the live-config seam (an in-process config write would be a no-op).
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    saved_cfg = ray_tpu.get(proxy.apply_config.remote(
        {"serve_max_queued_requests": 16}), timeout=30)
    try:
        def prompt_for(tag: str, i: int) -> str:
            return f"req {tag}-{i}: " + "abcdefgh" * (4 + i % 3)

        # Warm every prompt shape for the quiet/noisy/hot tenants (the
        # p95 cells must measure queueing and adapter mixing, not
        # first-touch XLA). "cold" is deliberately NOT warmed: its
        # adapter must hot-load mid-storm through the LRU.
        warm = []
        for i in range(6):
            warm.append(threading.Thread(
                target=_one_request,
                args=(addr, route, prompt_for("warm", i), max_tokens,
                      None, "quiet", 180.0), daemon=True))
        for i, model in enumerate(("noisy", "hot", "noisy", "hot")):
            warm.append(threading.Thread(
                target=_one_request,
                args=(addr, route, prompt_for("warm", 6 + i), max_tokens,
                      model, None, 180.0), daemon=True))
        for t in warm:
            t.start()
        for t in warm:
            t.join(timeout=240)

        def quiet_loop(tag: str) -> list[dict]:
            return [_one_request(addr, route, prompt_for(tag, i),
                                 max_tokens, None, "quiet", 120.0)
                    for i in range(quiet_n)]

        # ---- solo: the quiet tenant alone on the deployment.
        t0 = time.perf_counter()
        solo = quiet_loop("solo")
        solo_elapsed = time.perf_counter() - t0
        solo_ttfts = sorted(r["ttft_s"] for r in solo
                            if r["status"] == "200" and r["ttft_s"])
        solo_walls = sorted(r["wall_s"] for r in solo
                            if r["status"] == "200")
        if not solo_ttfts:
            raise RuntimeError("quiet tenant served 0 solo requests")
        out["tenant_quiet_p95_ttft_ms_solo"] = round(
            1000 * _pct(solo_ttfts, 0.95), 1)

        # ---- noisy: closed-loop storm (8 clients, far beyond the
        # 4-slot capacity) on the quota-limited tenant while the quiet
        # tenant repeats the SAME closed loop.
        stop = threading.Event()
        noisy_results: list[dict] = []
        nlock = threading.Lock()

        def noisy_client(cid: int) -> None:
            j = 0
            while not stop.is_set():
                r = _one_request(addr, route, prompt_for(f"n{cid}", j),
                                 max_tokens, "noisy", None, 120.0)
                j += 1
                with nlock:
                    noisy_results.append(r)
                if r["status"] != "200":
                    # honest Retry-After pacing keeps the storm open-loop
                    # bounded instead of a tight 429 spin
                    stop.wait(min(2.0, float(r["retry_after"] or 1)))

        nthreads = [threading.Thread(target=noisy_client, args=(i,),
                                     daemon=True) for i in range(8)]
        for t in nthreads:
            t.start()
        time.sleep(0.5)  # let the storm reach the router queue first
        noisy = quiet_loop("noisy")
        stop.set()
        for t in nthreads:
            t.join(timeout=150)
        noisy_ttfts = sorted(r["ttft_s"] for r in noisy
                             if r["status"] == "200" and r["ttft_s"])
        if noisy_ttfts:
            out["tenant_quiet_p95_ttft_ms_noisy"] = round(
                1000 * _pct(noisy_ttfts, 0.95), 1)
        out["tenant_quiet_noisy_200s_cfg"] = len(noisy_ttfts)
        out["tenant_noisy_quota_429_cfg"] = sum(
            1 for r in noisy_results if r["status"] == "429")
        out["tenant_noisy_admitted_cfg"] = sum(
            1 for r in noisy_results if r["status"] == "200")

        # ---- mixed 2× storm: hot (adapter resident) vs cold (adapter
        # hot-loads through the LRU mid-storm), open-loop arrivals at
        # ~2× the solo-derived capacity, alternating tenants.
        solo_rps = len(solo_walls) / max(1e-3, solo_elapsed)
        offered_rps = 2.0 * solo_rps * max_slots
        n_offered = min(64, max(12, int(offered_rps * storm_s)))
        budget_s = 4.0 * _pct(solo_walls, 0.5) + 2.0
        results: list[dict | None] = [None] * n_offered
        t0 = time.perf_counter()

        def fire(i: int) -> None:
            delay = t0 + i / offered_rps - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            model = "hot" if i % 2 == 0 else "cold"
            results[i] = _one_request(addr, route,
                                      prompt_for(f"s{model}", i),
                                      max_tokens, model, None, 120.0)

        threads = [threading.Thread(target=fire, args=(i,), daemon=True)
                   for i in range(n_offered)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)

        for tenant in ("hot", "cold"):
            mine = [r for i, r in enumerate(results)
                    if r is not None
                    and ("hot" if i % 2 == 0 else "cold") == tenant]
            good = sum(1 for r in mine if r["status"] == "200"
                       and r["wall_s"] is not None
                       and r["wall_s"] <= budget_s)
            out[f"tenant_goodput_frac_{tenant}"] = round(
                good / max(1, len(mine)), 4)
        out["tenant_storm_offered_cfg"] = n_offered
    finally:
        try:
            ray_tpu.get(proxy.apply_config.remote(saved_cfg), timeout=30)
        except Exception:
            pass
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
    return out


if __name__ == "__main__":
    print(json.dumps(run_tenancy_bench()))
