"""Seeded, declarative fault plans.

A :class:`FaultPlan` is a list of fault rules (RPC drops/delays/failures
per method, node-pair partitions and GCS blackouts, worker
kill-on-Nth-lease, spill-disk write errors, object-store allocation
failures). ``plan.compile(seed)`` turns it into a :class:`FaultSchedule`
— every probabilistic decision pre-drawn from a per-rule RNG seeded by
``(seed, rule index, rule identity)`` into explicit call indices. The
schedule is what makes chaos *reproducible*: the same plan + seed
compiles to a byte-identical schedule on every machine, and the engine
consults only the schedule (never a live RNG) at injection time.

Jepsen-style fault schedules over FoundationDB-style determinism: the
plan says *what* can break; the seed pins *exactly when*.
"""

from __future__ import annotations

import hashlib
import json
import random
import threading
from typing import Callable

from ..core.rpc import RpcChaos
from .clock import get_clock

# Fault kinds a plan may declare.
KIND_RPC = "rpc"                    # drop/fail/delay one RPC method
KIND_KILL_WORKER = "kill_worker"    # SIGKILL the worker of the Nth lease
KIND_SPILL_ERROR = "spill_error"    # fail a spill-file disk write
KIND_STORE_FULL = "store_full"      # fail an object-store allocation
KIND_PARTITION = "partition"        # block a peer address set for a window
KIND_GCS_BLACKOUT = "gcs_blackout"  # partition targeting the GCS endpoint
KIND_HTTP_INGRESS = "http_ingress"  # drop/delay at the serve HTTP proxy
KIND_KILL_LOOP = "kill_loop_stage"  # os._exit a loop stage at its Nth tick
KIND_PREEMPT = "preempt_slice"      # GCE preemption notice at a node's Nth tick
KIND_REPLICA_DELAY = "replica_delay"  # stall a serve replica's handles

_COUNTED_KINDS = (KIND_RPC, KIND_KILL_WORKER, KIND_SPILL_ERROR,
                  KIND_STORE_FULL, KIND_HTTP_INGRESS, KIND_KILL_LOOP,
                  KIND_PREEMPT, KIND_REPLICA_DELAY)
_WINDOW_KINDS = (KIND_PARTITION, KIND_GCS_BLACKOUT)

# How many future calls a probabilistic rule pre-draws decisions for.
DEFAULT_HORIZON = 4096


class FaultPlanError(ValueError):
    pass


class FaultPlan:
    """Declarative schedule of faults (YAML/dict), seed-compiled."""

    def __init__(self, name: str, faults: list[dict],
                 description: str = ""):
        self.name = name
        self.description = description
        self.faults = [dict(f) for f in faults]
        for i, fault in enumerate(self.faults):
            kind = fault.get("kind")
            if kind in (KIND_RPC, KIND_HTTP_INGRESS):
                if kind == KIND_RPC and not fault.get("method"):
                    raise FaultPlanError(f"faults[{i}]: rpc rule needs a method")
                where = fault.get("where", "request")
                if where not in ("request", "response", "client"):
                    raise FaultPlanError(
                        f"faults[{i}]: where must be request|response|client")
            elif kind == KIND_REPLICA_DELAY:
                if float(fault.get("delay_ms") or 0.0) <= 0:
                    raise FaultPlanError(
                        f"faults[{i}]: replica_delay needs delay_ms")
            elif kind in (KIND_KILL_WORKER, KIND_SPILL_ERROR, KIND_STORE_FULL,
                          KIND_KILL_LOOP, KIND_PREEMPT):
                pass
            elif kind in _WINDOW_KINDS:
                if float(fault.get("duration_s", 0)) <= 0:
                    raise FaultPlanError(f"faults[{i}]: window needs duration_s")
            else:
                raise FaultPlanError(f"faults[{i}]: unknown kind {kind!r}")

    # ------------------------------------------------------------- loading
    @classmethod
    def from_dict(cls, data: dict) -> "FaultPlan":
        return cls(name=data.get("name", "unnamed"),
                   faults=list(data.get("faults") or []),
                   description=data.get("description", ""))

    @classmethod
    def from_yaml(cls, path: str) -> "FaultPlan":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    def to_dict(self) -> dict:
        return {"name": self.name, "description": self.description,
                "faults": [dict(f) for f in self.faults]}

    # ------------------------------------------------------------ compiling
    def compile(self, seed: int = 0,
                horizon: int = DEFAULT_HORIZON) -> "FaultSchedule":
        """Pre-draw every probabilistic decision into explicit call
        indices. Deterministic: same plan + seed -> byte-identical
        schedule (``FaultSchedule.canonical_bytes()``)."""
        rules = []
        for i, fault in enumerate(self.faults):
            rule = dict(fault)
            kind = rule["kind"]
            if kind in _COUNTED_KINDS:
                nth = int(rule.get("nth") or rule.get("nth_lease") or 0)
                prob = float(rule.get("prob") or 0.0)
                cap = int(rule.get("max_injections") or 0)
                if nth:
                    rule["nth"] = nth
                elif prob:
                    rng = random.Random(
                        f"{seed}:{i}:{kind}:{rule.get('method', '')}:"
                        f"{rule.get('where', '')}")
                    indices = [k for k in range(1, horizon + 1)
                               if rng.random() < prob]
                    if cap:
                        indices = indices[:cap]
                    rule["indices"] = indices
                elif not float(rule.get("delay_ms") or 0.0):
                    raise FaultPlanError(
                        f"faults[{i}]: needs nth, prob, or delay_ms")
            rules.append(rule)
        return FaultSchedule(self.to_dict(), seed, rules)


class FaultSchedule:
    """A compiled plan: the full fault timetable, independent of runtime."""

    def __init__(self, plan: dict, seed: int, rules: list[dict]):
        self.plan = plan
        self.seed = seed
        self.rules = rules

    def to_dict(self) -> dict:
        return {"plan": self.plan, "seed": self.seed, "rules": self.rules}

    def canonical_bytes(self) -> bytes:
        """Canonical serialization — the byte-identical artifact two runs
        of ``cli chaos run <plan> --seed N`` must agree on."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")).encode()

    def digest(self) -> str:
        return hashlib.sha1(self.canonical_bytes()).hexdigest()[:16]


class PlanChaos(RpcChaos):
    """Chaos engine driven by a compiled :class:`FaultSchedule`.

    Installed process-wide via ``core.rpc.set_chaos``; the RPC layer,
    raylet, object store, and serve proxy consult it at their injection
    points. All decisions are schedule lookups on per-rule call counters
    — no RNG at runtime — so a replay with the same call sequence injects
    the same faults.
    """

    def __init__(self, schedule: FaultSchedule,
                 publish: Callable[[str, str, str], None] | None = None,
                 partition_peers: dict[int, list[str]] | None = None):
        super().__init__("", seed=schedule.seed)
        self.schedule = schedule
        self._publish = publish
        self._counts: dict[int, int] = {}
        self._index_sets: dict[int, frozenset] = {
            idx: frozenset(rule.get("indices") or ())
            for idx, rule in enumerate(schedule.rules)}
        self._plock = threading.Lock()
        self._installed_at = get_clock().now()
        # rule index -> resolved peer addresses (partitions/blackouts);
        # filled by the runner, which knows the live cluster topology.
        self._partition_peers = dict(partition_peers or {})
        self.injection_log: list[dict] = []

    # ------------------------------------------------------------ internals
    def _fire(self, idx: int, rule: dict, kind: str, detail: str) -> None:
        method = rule.get("method", "") or rule.get("kind", "")
        self.record_injection(kind, method)
        with self._plock:
            if len(self.injection_log) < 1000:
                self.injection_log.append(
                    {"rule": idx, "kind": kind, "method": method,
                     "detail": detail})
        if self._publish is not None:
            try:
                self._publish(kind, method, detail)
            except Exception:
                pass

    def _take(self, idx: int, rule: dict) -> bool:
        """Advance rule ``idx``'s call counter; True if this call index is
        in the compiled schedule (and under the injection cap)."""
        with self._plock:
            n = self._counts.get(idx, 0) + 1
            self._counts[idx] = n
            cap = int(rule.get("max_injections") or 0)
            fired = self._fired_count(idx)
            if cap and fired >= cap:
                return False
            if rule.get("nth"):
                return n % int(rule["nth"]) == 0
            return n in self._index_sets.get(idx, frozenset())

    def _fired_count(self, idx: int) -> int:
        return sum(1 for e in self.injection_log if e["rule"] == idx)

    def _matching(self, kind: str, method: str = "", where: str = "",
                  tag: str = ""):
        for idx, rule in enumerate(self.schedule.rules):
            if rule["kind"] != kind:
                continue
            if kind in (KIND_RPC,):
                if rule.get("method") not in ("*", method):
                    continue
                if (rule.get("where", "request")) != where:
                    continue
                if rule.get("tag") and rule["tag"] != tag:
                    continue
            yield idx, rule

    # ------------------------------------------------------- decision hooks
    def should_fail_request(self, method: str, tag: str = "") -> bool:
        for idx, rule in self._matching(KIND_RPC, method, "request", tag):
            if not float(rule.get("delay_ms") or 0.0) and self._take(idx, rule):
                self._fire(idx, rule, "rpc_request_drop", method)
                return True
        return False

    def should_fail_response(self, method: str, tag: str = "") -> bool:
        for idx, rule in self._matching(KIND_RPC, method, "response", tag):
            if self._take(idx, rule):
                self._fire(idx, rule, "rpc_response_drop", method)
                return True
        return False

    def should_drop_client_send(self, method: str) -> bool:
        for idx, rule in self._matching(KIND_RPC, method, "client"):
            if self._take(idx, rule):
                self._fire(idx, rule, "rpc_client_drop", method)
                return True
        return False

    def request_delay_s(self, method: str, tag: str = "") -> float:
        for idx, rule in self._matching(KIND_RPC, method, "request", tag):
            delay_ms = float(rule.get("delay_ms") or 0.0)
            if delay_ms and self._take(idx, rule):
                self._fire(idx, rule, "rpc_delay", method)
                return delay_ms / 1000.0
        return 0.0

    def _window_active(self, rule: dict) -> bool:
        now = get_clock().now() - self._installed_at
        start = float(rule.get("start_s") or 0.0)
        return start <= now < start + float(rule["duration_s"])

    def peer_blocked(self, address: str) -> bool:
        for idx, rule in enumerate(self.schedule.rules):
            if rule["kind"] not in _WINDOW_KINDS:
                continue
            if not self._window_active(rule):
                continue
            peers = self._partition_peers.get(idx) or []
            if address in peers:
                kind = ("gcs_blackout" if rule["kind"] == KIND_GCS_BLACKOUT
                        else "partition")
                self._fire(idx, rule, kind, address)
                return True
        return False

    def take_kill_on_lease(self, node_id: str = "") -> bool:
        for idx, rule in self._matching(KIND_KILL_WORKER):
            if rule.get("node") and not node_id.startswith(rule["node"]):
                continue
            if self._take(idx, rule):
                self._fire(idx, rule, "kill_worker", node_id[:12])
                return True
        return False

    def take_kill_loop_tick(self) -> bool:
        """One compiled-loop stage tick in this process: die here? The
        tick index is the deterministic coordinate (``nth``-style rules
        fire at exactly the Nth tick the schedule pre-drew)."""
        for idx, rule in self._matching(KIND_KILL_LOOP):
            if self._take(idx, rule):
                self._fire(idx, rule, "kill_loop_stage", "")
                return True
        return False

    def take_preempt_slice(self, node_id: str = "") -> bool:
        """One heartbeat tick on ``node_id``: does the GCE-style
        preemption notice land here now? Rules target a node-id prefix
        (``node``) or a runner-resolved ``target: node:<i>`` (i-th alive
        node at install time); a targeted rule whose target did not
        resolve never fires — so the bundled plan is a safe no-op on a
        cluster too small to have the targeted node. Only MATCHING ticks
        advance the rule counter, so ``nth`` is deterministic per
        targeted node regardless of how many raylets share the engine."""
        for idx, rule in self._matching(KIND_PREEMPT):
            if rule.get("node"):
                if not node_id.startswith(rule["node"]):
                    continue
            elif rule.get("target"):
                targets = self._partition_peers.get(idx) or []
                if not any(node_id.startswith(t) for t in targets):
                    continue
            if self._take(idx, rule):
                self._fire(idx, rule, "preempt_slice", node_id[:12])
                return True
        return False

    def replica_delay_s(self, replica_id: str = "") -> float:
        """One serve-replica handle in this process: how long to stall
        it. Rules target a replica-id prefix (``replica``, e.g.
        "app#dep#2") or every replica when absent; ``nth: 1`` stalls
        every handle — the deterministic stand-in for a replica gone
        slow (the overload plan's delayed-replica fault)."""
        for idx, rule in self._matching(KIND_REPLICA_DELAY):
            if rule.get("replica") and \
                    not replica_id.startswith(rule["replica"]):
                continue
            if self._take(idx, rule):
                self._fire(idx, rule, "replica_delay", replica_id[:32])
                return float(rule.get("delay_ms") or 0.0) / 1000.0
        return 0.0

    def maybe_fail_spill(self) -> bool:
        for idx, rule in self._matching(KIND_SPILL_ERROR):
            if self._take(idx, rule):
                self._fire(idx, rule, "spill_error", "")
                return True
        return False

    def maybe_fail_store_create(self) -> bool:
        for idx, rule in self._matching(KIND_STORE_FULL):
            if self._take(idx, rule):
                self._fire(idx, rule, "store_full", "")
                return True
        return False

    def http_ingress_fault(self) -> tuple[bool, float]:
        """(drop?, delay_s) for one serve HTTP request."""
        drop, delay = False, 0.0
        for idx, rule in self._matching(KIND_HTTP_INGRESS):
            if self._take(idx, rule):
                delay_ms = float(rule.get("delay_ms") or 0.0)
                if delay_ms:
                    delay = delay_ms / 1000.0
                    self._fire(idx, rule, "http_delay", "http.ingress")
                else:
                    drop = True
                    self._fire(idx, rule, "http_drop", "http.ingress")
        return drop, delay


# Bundled plans: each must end RecoveryVerifier-green (tests/test_chaos.py
# runs the fast ones tier-1; the sweep exercises them across seeds).
BUILTIN_PLANS: dict[str, dict] = {
    "lease-reply-drop": {
        "name": "lease-reply-drop",
        "description": "Drop every 2nd RequestWorkerLease reply (the "
                       "ROADMAP-1c cascade trigger); owners must retry and "
                       "the raylet must reclaim the orphaned grants.",
        "faults": [
            {"kind": "rpc", "method": "RequestWorkerLease",
             "where": "response", "nth": 2, "max_injections": 4},
        ],
    },
    "push-client-drop": {
        "name": "push-client-drop",
        "description": "Drop task pushes on the owner side before they "
                       "reach the worker; task retries must succeed.",
        "faults": [
            {"kind": "rpc", "method": "PushTask", "where": "client",
             "nth": 2, "max_injections": 3},
        ],
    },
    "worker-kill": {
        "name": "worker-kill",
        "description": "SIGKILL the worker of the 1st lease; the owner "
                       "retries on a fresh worker.",
        "faults": [
            {"kind": "kill_worker", "nth_lease": 1, "max_injections": 1},
        ],
    },
    "spill-disk-error": {
        "name": "spill-disk-error",
        "description": "Fail the first 2 spill-file writes; objects must "
                       "stay restorable from the pending-write buffer.",
        "faults": [
            {"kind": "spill_error", "nth": 1, "max_injections": 2},
        ],
    },
    "gcs-blackout": {
        "name": "gcs-blackout",
        "description": "Black out the GCS endpoint for 2s; clients must "
                       "ride it out on retry backoff and reconnect.",
        "faults": [
            {"kind": "gcs_blackout", "start_s": 0.0, "duration_s": 2.0},
        ],
    },
    "slice-preempt": {
        "name": "slice-preempt",
        "description": "GCE-style preemption notice on the 2nd alive node "
                       "at its 2nd heartbeat tick: the raylet drains, the "
                       "GCS publishes node_preempted, and work re-routes "
                       "to survivors. No-ops when the targeted node does "
                       "not exist (single-node clusters).",
        "faults": [
            {"kind": "preempt_slice", "nth": 2, "max_injections": 1,
             "target": "node:1"},
        ],
    },
    "overload-storm": {
        "name": "overload-storm",
        "description": "Overload chaos: every handle on replica #2 of "
                       "the targeted deployment stalls 400 ms (a replica "
                       "gone slow under a thundering herd). Driven with "
                       "a deterministic burst arrival schedule + request "
                       "deadlines, the system must shed/expire honestly "
                       "and drain back to a verifier-green state with "
                       "page-pool refcounts at baseline.",
        "faults": [
            {"kind": "replica_delay", "replica": "overload#LLMDeployment#2",
             "nth": 1, "delay_ms": 400},
        ],
    },
    "actor-storm": {
        "name": "actor-storm",
        "description": "Actor-creation storm chaos: while a storm drives "
                       "hundreds of dedicated leases, SIGKILL the worker "
                       "of every 40th lease (3x) and deliver a GCE-style "
                       "preemption notice to the 2nd alive node mid-storm."
                       " Actor restarts must absorb the kills, survivors "
                       "must re-place off the draining node, and the "
                       "zygote pools must drain/refill to baseline. "
                       "No-ops the preempt rule on single-node clusters.",
        "faults": [
            {"kind": "kill_worker", "nth_lease": 40, "max_injections": 3},
            {"kind": "preempt_slice", "nth": 3, "max_injections": 1,
             "target": "node:1"},
        ],
    },
    "mixed-seeded": {
        "name": "mixed-seeded",
        "description": "Seeded probabilistic mix for randomized sweeps: "
                       "lease-reply drops + push drops + a worker kill.",
        "faults": [
            {"kind": "rpc", "method": "RequestWorkerLease",
             "where": "response", "prob": 0.3, "max_injections": 3},
            {"kind": "rpc", "method": "PushTask", "where": "client",
             "prob": 0.2, "max_injections": 3},
            {"kind": "kill_worker", "nth_lease": 3, "max_injections": 1},
        ],
    },
}


def load_plan(plan: "FaultPlan | dict | str") -> FaultPlan:
    """Accept a FaultPlan, a plan dict, a builtin plan name, or a path to
    a YAML file."""
    if isinstance(plan, FaultPlan):
        return plan
    if isinstance(plan, dict):
        return FaultPlan.from_dict(plan)
    if plan in BUILTIN_PLANS:
        return FaultPlan.from_dict(BUILTIN_PLANS[plan])
    return FaultPlan.from_yaml(plan)
