"""Cluster-wide virtual time.

Deterministic-simulation support in the FoundationDB style: every
timeout-driven control loop (lease-wedge watchdog, orphan-lease reclaim,
GCS leak watcher, serve restart backoff, elastic-train debounce) reads
time through this module instead of ``time.monotonic`` directly. Under
the default :class:`WallClock` that is byte-for-byte the old behavior;
installing a :class:`VirtualClock` (directly via :func:`set_clock`, or
in every spawned process via the ``chaos_clock`` config entry /
``RAY_TPU_chaos_clock`` env var) lets a chaos test replay a multi-minute
timeout cascade in milliseconds, deterministically.

The clock intentionally does NOT replace the asyncio event-loop clock or
RPC deadlines: transport-level timeouts stay on wall time so a virtual
clock can run arbitrarily fast without fabricating transport failures.
"""

from __future__ import annotations

import asyncio
import threading
import time

__all__ = [
    "Clock",
    "WallClock",
    "VirtualClock",
    "get_clock",
    "set_clock",
    "now",
    "sleep",
]


class Clock:
    """Interface: monotonic ``now()`` seconds + an async ``sleep``."""

    def now(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    async def sleep(self, duration: float) -> None:  # pragma: no cover
        raise NotImplementedError


class WallClock(Clock):
    """Real time: ``time.monotonic`` / ``asyncio.sleep`` (the default)."""

    def now(self) -> float:
        return time.monotonic()

    async def sleep(self, duration: float) -> None:
        await asyncio.sleep(duration)


class VirtualClock(Clock):
    """Virtual time that can run faster than (or detached from) wall time.

    ``rate`` scales real elapsed time into virtual seconds (``rate=60``
    replays one virtual minute per real second); ``rate=0`` freezes time
    entirely so only explicit :meth:`advance` calls move it — the fully
    deterministic mode. ``sleep`` polls in tiny real slices so sleepers
    on ANY event loop or thread observe advances without coordination
    (this runtime runs raylets, the GCS, and the driver on separate
    loops/threads in one process).
    """

    def __init__(self, start: float = 0.0, rate: float = 0.0,
                 tick_s: float = 0.002):
        self._base = start
        self._rate = float(rate)
        self._offset = 0.0
        self._t0 = time.monotonic()
        self._tick_s = tick_s
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._base + self._offset + (
                time.monotonic() - self._t0) * self._rate

    def advance(self, duration: float) -> None:
        """Jump virtual time forward by ``duration`` seconds."""
        with self._lock:
            self._offset += float(duration)

    async def sleep(self, duration: float) -> None:
        deadline = self.now() + duration
        while self.now() < deadline:
            await asyncio.sleep(self._tick_s)

    def sleep_sync(self, duration: float) -> None:
        """Blocking variant for thread-based loops (serve controller)."""
        deadline = self.now() + duration
        while self.now() < deadline:
            time.sleep(self._tick_s)


_WALL = WallClock()
_clock: Clock | None = None
_clock_lock = threading.Lock()


def _from_spec(spec: str) -> Clock:
    """``"" | "wall" -> WallClock``; ``"virtual" | "virtual:RATE"`` ->
    VirtualClock (default rate 0 = manual advance only)."""
    spec = (spec or "").strip()
    if not spec or spec == "wall":
        return _WALL
    if spec.startswith("virtual"):
        _, _, rate = spec.partition(":")
        return VirtualClock(rate=float(rate) if rate else 0.0)
    raise ValueError(f"Unknown chaos_clock spec: {spec!r}")


def get_clock() -> Clock:
    """The process clock; initialized from the ``chaos_clock`` config
    entry (so workers spawned with ``RAY_TPU_chaos_clock=virtual:50``
    inherit virtual time) and replaceable via :func:`set_clock`."""
    global _clock
    if _clock is None:
        with _clock_lock:
            if _clock is None:
                try:
                    from ..core.config import get_config

                    _clock = _from_spec(get_config().chaos_clock)
                except Exception:
                    _clock = _WALL
    return _clock


def set_clock(clock: Clock | None) -> None:
    """Install a clock for this process (tests / chaos runner).
    ``None`` resets to the config-derived default."""
    global _clock
    with _clock_lock:
        _clock = clock


def now() -> float:
    return get_clock().now()


async def sleep(duration: float) -> None:
    await get_clock().sleep(duration)


def sleep_sync(duration: float) -> None:
    clock = get_clock()
    if isinstance(clock, VirtualClock):
        clock.sleep_sync(duration)
    else:
        time.sleep(duration)
