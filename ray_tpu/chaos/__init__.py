"""Deterministic chaos subsystem.

Three pieces (see ``chaos/plan.py``, ``chaos/clock.py``,
``chaos/verifier.py``):

  * **FaultPlan** — a seeded, declarative schedule of faults (RPC
    drop/delay/fail per method, node-pair partitions, GCS blackouts,
    worker kill-on-Nth-lease, spill-disk write errors) compiled into a
    byte-identical :class:`FaultSchedule` and installed as the process's
    chaos engine (driving ``core.rpc.RpcChaos`` plus injection points in
    the raylet, object store, and serve proxy).
  * **VirtualClock** — cluster-wide virtual time for the timeout-driven
    control loops, so wedge watchdogs / leak watchers / backoffs replay
    deterministically and fast.
  * **RecoveryVerifier** — asserts the cluster heals after every plan:
    all tasks terminal, lease queues drained, refcounts at baseline, no
    orphaned ErrorEvents.

Entry points: :func:`run_plan` (also ``cli chaos run <plan.yaml> --seed
N``), :func:`install`/:func:`uninstall` for manual control, and
``BUILTIN_PLANS`` for the bundled scenarios.
"""

from .clock import Clock, VirtualClock, WallClock, get_clock, set_clock
from .plan import (
    BUILTIN_PLANS,
    FaultPlan,
    FaultPlanError,
    FaultSchedule,
    PlanChaos,
    load_plan,
)
from .runner import active_plan, default_workload, install, run_plan, uninstall
from .verifier import ChaosVerificationError, RecoveryVerifier, VerifyResult

__all__ = [
    "BUILTIN_PLANS",
    "ChaosVerificationError",
    "Clock",
    "FaultPlan",
    "FaultPlanError",
    "FaultSchedule",
    "PlanChaos",
    "RecoveryVerifier",
    "VerifyResult",
    "VirtualClock",
    "WallClock",
    "active_plan",
    "default_workload",
    "get_clock",
    "install",
    "load_plan",
    "run_plan",
    "set_clock",
    "uninstall",
]
