"""Chaos runner: install a compiled fault schedule, drive a workload,
then verify recovery.

``run_plan(plan, seed=N)`` is the one-call form surfaced as
``ray_tpu.chaos.run_plan()`` and ``cli chaos run <plan.yaml> --seed N``.
While a plan is installed its identity is registered in the GCS KV
(``chaos:active_plan``) so every client — and ``cli doctor`` — can tell
injected pain from real pain.
"""

from __future__ import annotations

import json
import logging
import time

from ..core.rpc import get_chaos, set_chaos
from .plan import FaultPlan, FaultSchedule, PlanChaos, load_plan
from .verifier import RecoveryVerifier

logger = logging.getLogger(__name__)

ACTIVE_PLAN_KEY = "chaos:active_plan"


def _resolve_partition_peers(schedule: FaultSchedule) -> dict[int, list[str]]:
    """Resolve window/targeted rules' abstract targets into live
    identities. ``gcs_blackout`` / ``target: gcs`` -> the GCS endpoint;
    ``target: node:<i>`` -> the i-th alive raylet (its ADDRESS for
    partitions, its NODE ID for ``preempt_slice``); explicit ``peers``
    lists pass through. A target that does not resolve stays absent —
    the rule then never fires (safe no-op on too-small clusters)."""
    from ..core.worker import global_worker

    peers: dict[int, list[str]] = {}
    nodes = None

    def _alive_nodes():
        nonlocal nodes
        if nodes is None:
            from ..util import state

            nodes = [n for n in state.list_nodes() if n["state"] == "ALIVE"]
        return nodes

    for idx, rule in enumerate(schedule.rules):
        if rule["kind"] == "gcs_blackout" or rule.get("target") == "gcs":
            peers[idx] = [global_worker().gcs_address]
        elif rule["kind"] == "partition":
            if rule.get("peers"):
                peers[idx] = list(rule["peers"])
            elif str(rule.get("target", "")).startswith("node:"):
                i = int(rule["target"].split(":", 1)[1])
                if i < len(_alive_nodes()):
                    peers[idx] = [_alive_nodes()[i]["address"]]
        elif rule["kind"] == "preempt_slice":
            if str(rule.get("target", "")).startswith("node:"):
                i = int(rule["target"].split(":", 1)[1])
                if i < len(_alive_nodes()):
                    peers[idx] = [_alive_nodes()[i]["node_id"]]
    return peers


def _publish_injection(plan_name: str, seed: int):
    """Build the per-injection ErrorEvent publisher: every injected fault
    lands on the diagnostics channel tagged ``chaos`` so ``list_errors()``
    and traces can separate it from organic failures."""
    from ..diagnostics.errors import publish_error_to_driver

    seen_windows: set[tuple] = set()
    published = [0]

    def publish(kind: str, method: str, detail: str) -> None:
        # Window faults (partitions/blackouts) publish ONCE per rule: the
        # publish RPC itself crosses the blocked endpoint, and a
        # per-blocked-call event would recurse — each suppressed call
        # still counts in the metric and the injection log.
        if kind in ("gcs_blackout", "partition"):
            if (kind, method) in seen_windows:
                return
            seen_windows.add((kind, method))
        if published[0] >= 200:
            return  # bounded: chaos must not flood the error channel
        published[0] += 1
        publish_error_to_driver(
            "chaos_injection",
            f"chaos[{plan_name}#{seed}]: injected {kind}"
            + (f" on {method}" if method else "")
            + (f" ({detail})" if detail else ""),
            source="chaos",
            extra={"chaos": True, "plan": plan_name, "seed": seed,
                   "kind": kind, "method": method})

    return publish


def install(plan, seed: int = 0, publish: bool = True) -> PlanChaos:
    """Compile + install ``plan`` as this process's chaos engine and
    register it in the GCS KV. Returns the live engine."""
    plan = load_plan(plan)
    schedule = plan.compile(seed)
    engine = PlanChaos(
        schedule,
        publish=_publish_injection(plan.name, seed) if publish else None,
        partition_peers=_resolve_partition_peers(schedule))
    set_chaos(engine)
    try:
        from ..core.worker import global_worker

        global_worker()._gcs_call("KvPut", {
            "key": ACTIVE_PLAN_KEY,
            "value": json.dumps({
                "name": plan.name, "seed": seed,
                "digest": schedule.digest(),
                "installed_at": time.time(),
            }).encode()})
    except Exception:
        pass  # no cluster (schedule-only use): engine still installs
    logger.warning("chaos: installed plan %r seed=%d digest=%s",
                   plan.name, seed, schedule.digest())
    return engine


def uninstall() -> None:
    """Remove the installed plan (reverts to the env-spec chaos, if any)."""
    set_chaos(None)
    try:
        from ..core.worker import global_worker

        global_worker()._gcs_call("KvDel", {"key": ACTIVE_PLAN_KEY})
    except Exception:
        pass


def active_plan() -> dict | None:
    """The cluster's registered FaultPlan, if one is installed (readable
    from any connected client — powers the ``cli doctor`` banner)."""
    try:
        from ..core.worker import global_worker

        reply = global_worker()._gcs_call("KvGet", {"key": ACTIVE_PLAN_KEY})
        if reply.get("found"):
            return json.loads(reply["value"])
    except Exception:
        pass
    return None


def default_workload() -> dict:
    """A small task workload exercising retry, plasma, and lineage paths
    under fault: used when ``run_plan`` is not given a workload."""
    import ray_tpu

    @ray_tpu.remote(max_retries=5)
    def _chaos_probe(i):
        return i * i

    @ray_tpu.remote(max_retries=5)
    def _chaos_blob(_i):
        import numpy as np

        return np.zeros(64 * 1024, dtype=np.float32)  # plasma-sized

    refs = [_chaos_probe.remote(i) for i in range(8)]
    refs += [_chaos_blob.remote(i) for i in range(2)]
    ok, failures = 0, 0
    for ref in refs:
        try:
            ray_tpu.get(ref, timeout=120)
            ok += 1
        except Exception:
            failures += 1
    del refs
    return {"tasks": ok + failures, "ok": ok, "failures": failures}


def run_plan(plan, seed: int = 0, workload=None, verify: bool = True,
             verify_timeout_s: float = 60.0,
             allowed_error_types=()) -> dict:
    """Run one seeded chaos scenario end to end:

    1. snapshot the verifier baseline,
    2. compile + install the plan's fault schedule,
    3. drive the workload (default: :func:`default_workload`),
    4. uninstall the plan,
    5. verify recovery (tasks terminal, lease queues drained, refcounts
       back to baseline, no orphaned errors).

    Returns the chaos report; raises ``ChaosVerificationError`` when
    ``verify=True`` and an invariant fails.
    """
    plan = load_plan(plan)
    schedule = plan.compile(seed)
    verifier = RecoveryVerifier(timeout_s=verify_timeout_s,
                                allowed_error_types=allowed_error_types)
    baseline = verifier.snapshot_baseline()
    engine = install(plan, seed)
    try:
        workload_report = (workload or default_workload)()
    finally:
        uninstall()
    report = {
        "plan": plan.name,
        "seed": seed,
        "schedule_digest": schedule.digest(),
        "injections": {f"{k}:{m}" if m else k: n
                       for (k, m), n in engine.injections_total.items()},
        "injection_log": list(engine.injection_log),
        "workload": workload_report,
    }
    if verify:
        result = verifier.verify(baseline)
        report["verify"] = {"ok": result.ok, "checks": result.checks,
                            "violations": result.violations}
        result.raise_if_failed()
    return report
