"""Automated recovery verification after a chaos run.

After the fault plan is uninstalled the cluster must *heal*, and
"healed" is a checkable predicate, not a vibe:

  * every submitted task reaches a terminal state (FINISHED/FAILED) —
    nothing wedged in SUBMITTED/LEASED/RUNNING, and the driver's own
    pending-task table drains;
  * no wedged lease queues — every alive raylet's admission queue is
    empty once the workload quiesces;
  * the driver's reference table drains back to its pre-run baseline
    (chaos must not leak object refs);
  * no orphaned ErrorEvents — every fault-window error is either tagged
    ``chaos`` (extra.chaos=True / source "chaos") or one of the organic
    types the injected faults are *expected* to cause (task_failure from
    a killed worker, lease_orphan from a dropped lease reply, ...).

Reference inspiration: Jepsen's post-nemesis "final reads" phase and
FoundationDB's simulation invariant checks.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterable


# Organic error types an injected fault legitimately produces; anything
# else appearing during the fault window is an unexplained (orphaned)
# error and fails verification.
EXPECTED_ORGANIC_TYPES = frozenset({
    "task_failure", "actor_creation_failure", "replica_start_failure",
    "lease_orphan", "lease_wedge", "oom_kill", "memory_leak",
    # An injected preempt_slice rule drains a node: the GCS's
    # node_preempted notice is the designed consequence, not an orphan.
    "node_preempted",
})


@dataclass
class VerifyResult:
    ok: bool
    checks: dict = field(default_factory=dict)
    violations: list = field(default_factory=list)

    def raise_if_failed(self) -> "VerifyResult":
        if not self.ok:
            raise ChaosVerificationError(
                "recovery verification failed: " + "; ".join(self.violations))
        return self


class ChaosVerificationError(AssertionError):
    pass


def _is_actor_task_object(oid) -> bool:
    """True when the object is the return of an actor METHOD call or an
    actor creation: its TaskID embeds a non-nil ActorID unique part."""
    try:
        from ..core.ids import ActorID, TaskID

        tid = oid.task_id().binary()
        actor_unique = tid[TaskID.UNIQUE_BYTES:
                           TaskID.UNIQUE_BYTES + ActorID.UNIQUE_BYTES]
        return any(actor_unique)
    except Exception:
        return False


class RecoveryVerifier:
    """Asserts cluster invariants after a fault plan completes."""

    def __init__(self, timeout_s: float = 60.0, poll_s: float = 0.25,
                 allowed_error_types: Iterable[str] = ()):
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self.allowed_error_types = (
            EXPECTED_ORGANIC_TYPES | frozenset(allowed_error_types))

    # ------------------------------------------------------------- baseline
    def snapshot_baseline(self) -> dict:
        """Capture pre-run state the post-run invariants are judged
        against (existing refs, the number of errors already buffered)."""
        from ..core.worker import global_worker

        w = global_worker()
        return {
            "ref_ids": {oid.hex() for oid in list(w.refcounter._refs)},
            "num_errors": self._error_count(),
        }

    @staticmethod
    def _error_count() -> int:
        from ..core.worker import global_worker

        reply = global_worker()._gcs_call("ListErrors", {"limit": 10000})
        return len(reply.get("errors") or [])

    # ----------------------------------------------------------------- wait
    def _wait_for(self, predicate, timeout: float):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            result = predicate()
            if result:
                return result
            time.sleep(self.poll_s)
        return predicate()

    # --------------------------------------------------------------- verify
    def verify(self, baseline: dict | None = None) -> VerifyResult:
        from ..core.worker import global_worker
        from ..util import state

        w = global_worker()
        checks: dict = {}
        violations: list[str] = []

        # 1. Every submitted task settles: the driver's pending table
        #    drains, and the GCS-side last-status per task is terminal.
        #    Actor METHOD calls are exempt — long-poll methods (serve
        #    routers, pub/sub listeners) are legitimately RUNNING forever;
        #    normal tasks and actor creations must settle.
        from ..core.task_spec import TASK_KIND_ACTOR_TASK

        def _pending_settleable() -> list[str]:
            tm = w.task_manager
            with tm._lock:
                return [e["spec"].name for e in tm._pending.values()
                        if e["spec"].kind != TASK_KIND_ACTOR_TASK]

        def _stuck_in_gcs() -> list[dict]:
            return [t for t in state.list_tasks(limit=100_000)
                    if t.get("state") in ("SUBMITTED", "LEASED", "RUNNING")
                    and t.get("kind", 0) != TASK_KIND_ACTOR_TASK]

        def _tasks_terminal():
            if _pending_settleable():
                return None
            return {"pending": 0} if not _stuck_in_gcs() else None

        settled = self._wait_for(_tasks_terminal, self.timeout_s)
        checks["tasks_terminal"] = bool(settled)
        if not settled:
            stuck = _stuck_in_gcs()
            violations.append(
                f"tasks not terminal: {_pending_settleable()[:5]} pending "
                f"on the driver, {len(stuck)} non-terminal in the GCS "
                f"(e.g. {[t.get('name') for t in stuck[:5]]})")

        # 2. No wedged lease queues on any alive raylet.
        def _queues_drained():
            diag = state.cluster_diagnostics(error_limit=0)
            depths = {n.get("node_id", "?")[:12]: n.get("lease_queue_depth", 0)
                      for n in diag["nodes"] if "unreachable" not in n}
            return depths if all(d == 0 for d in depths.values()) else None

        drained = self._wait_for(_queues_drained, self.timeout_s / 2)
        checks["lease_queues_drained"] = bool(drained)
        if not drained:
            diag = state.cluster_diagnostics(error_limit=0)
            depths = {n.get("node_id", "?")[:12]: n.get("lease_queue_depth", 0)
                      for n in diag["nodes"]}
            violations.append(f"lease queues not drained: {depths}")

        # 3. The driver's reference table returns to baseline (new refs
        #    created during the run must all have been released). Returns
        #    of actor METHOD calls are exempt: background long-polls
        #    (serve routers, pub/sub listeners) legitimately keep one
        #    in-flight return ref alive at any instant.
        base_ids = (baseline or {}).get("ref_ids", set())

        def _leaked() -> list[str]:
            return [oid.hex() for oid in list(w.refcounter._refs)
                    if oid.hex() not in base_ids
                    and not _is_actor_task_object(oid)]

        refs_ok = self._wait_for(lambda: (True if not _leaked() else None),
                                 self.timeout_s / 2)
        checks["refcounts_drained"] = bool(refs_ok)
        if not refs_ok:
            leaked = [h[:12] for h in _leaked()]
            violations.append(
                f"{len(leaked)} refs leaked past baseline: {leaked[:8]}")

        # 4. No orphaned ErrorEvents: everything that fired during the
        #    window is chaos-tagged or an expected organic consequence.
        events = state.list_errors(limit=10_000)
        window = events[(baseline or {}).get("num_errors", 0):]
        orphaned = [
            e for e in window
            if not (e.get("extra") or {}).get("chaos")
            and e.get("source") != "chaos"
            and e.get("type") not in self.allowed_error_types
        ]
        checks["no_orphaned_errors"] = {
            "window": len(window),
            "chaos_tagged": sum(
                1 for e in window
                if (e.get("extra") or {}).get("chaos")
                or e.get("source") == "chaos"),
            "orphaned": len(orphaned),
        }
        if orphaned:
            violations.append(
                "orphaned (non-chaos, unexpected) errors: "
                + ", ".join(f"{e.get('source')}/{e.get('type')}"
                            for e in orphaned[:5]))

        return VerifyResult(ok=not violations, checks=checks,
                            violations=violations)
