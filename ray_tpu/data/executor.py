"""Physical plan + streaming executor.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py:48``
(loop :233,285; ``select_operator_to_run`` in streaming_executor_state.py:531).
The shape is the same in miniature: physical operators with input/output
queues, a driver scheduling loop that moves completed blocks downstream
and launches new tasks under per-op concurrency and a global in-flight
cap (backpressure). Map chains are fused into one task per block
(the optimizer's operator-fusion rule).

All-to-all ops (shuffle/sort/repartition) currently run as single
consolidation tasks, not a map-reduce exchange — fine for host-RAM-scale
data; the exchange planner is a later widening.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import numpy as np

from ..core import api as ray
from . import logical as L
from .block import BlockAccessor, batch_to_block, build_block, concat_blocks

# ---------------------------------------------------------------- map stages


@dataclasses.dataclass
class MapStage:
    kind: str  # "batches" | "rows" | "flat" | "filter"
    fn: Callable
    batch_format: str = "numpy"
    fn_kwargs: dict = dataclasses.field(default_factory=dict)


def _apply_stages(block, stages: list[MapStage]):
    for st in stages:
        acc = BlockAccessor.for_block(block)
        if st.kind == "batches":
            batch = acc.to_batch(st.batch_format)
            block = batch_to_block(st.fn(batch, **st.fn_kwargs))
        elif st.kind == "rows":
            block = build_block([st.fn(r) for r in acc.iter_rows()])
        elif st.kind == "flat":
            out = []
            for r in acc.iter_rows():
                out.extend(st.fn(r))
            block = build_block(out)
        elif st.kind == "filter":
            block = build_block([r for r in acc.iter_rows() if st.fn(r)])
        else:
            raise ValueError(st.kind)
    return block


def _read_task(fn):
    block = fn()
    import pyarrow as pa

    if not isinstance(block, pa.Table):
        block = batch_to_block(block)
    return block


def _map_task(stages: list[MapStage], block):
    return _apply_stages(block, stages)


class _MapWorker:
    """Stateful map_batches worker (reference: actor-pool map operator —
    ``_internal/execution/operators/actor_pool_map_operator.py``). A class
    fn is constructed ONCE per actor (e.g. loads a model); plain callables
    pass through."""

    def __init__(self, fn, constructor_args: tuple, constructor_kwargs: dict):
        self.fn = fn(*constructor_args, **constructor_kwargs) if isinstance(fn, type) else fn

    def apply(self, batch_format: str, fn_kwargs: dict, block):
        return _apply_stages(
            block, [MapStage("batches", self.fn, batch_format, fn_kwargs)]
        )


def _consolidate_task(op_kind: str, num_out: int, seed, sort_key, descending, *blocks):
    merged = concat_blocks(list(blocks))
    n = merged.num_rows
    if op_kind == "shuffle":
        rng = np.random.default_rng(seed)
        merged = merged.take(rng.permutation(n))
    elif op_kind == "sort":
        order = "descending" if descending else "ascending"
        merged = merged.sort_by([(sort_key, order)])
    if num_out <= 1:
        return merged
    bounds = [round(i * n / num_out) for i in range(num_out + 1)]
    return tuple(merged.slice(bounds[i], bounds[i + 1] - bounds[i]) for i in range(num_out))


# ------------------------------------------------------------- physical ops


class PhysicalOp:
    """Blocks are emitted in input order (completion order is buffered
    through a per-op reorder window), so downstream semantics — take(),
    zip-like joins, batch determinism — match the logical plan order."""

    def __init__(self, name: str):
        self.name = name
        self.input_queue: list = []  # upstream block refs
        self.in_flight: dict = {}  # ref -> seq
        self.output_queue: list = []
        self.upstream_done = False
        self._next_seq = 0
        self._emit_seq = 0
        self._completed: dict[int, Any] = {}

    def done(self) -> bool:
        return self.upstream_done and not self.input_queue and not self.in_flight

    def can_launch(self) -> bool:
        return bool(self.input_queue)

    def launch_one(self) -> list:
        raise NotImplementedError

    def _track(self, refs: list) -> list:
        for r in refs:
            self.in_flight[r] = self._next_seq
            self._next_seq += 1
        return refs

    def on_complete(self, ref) -> None:
        seq = self.in_flight.pop(ref)
        self._completed[seq] = ref
        while self._emit_seq in self._completed:
            self.output_queue.append(self._completed.pop(self._emit_seq))
            self._emit_seq += 1

    def close(self) -> None:
        """Release operator resources (actor pools) at stream end."""


class ReadPhysicalOp(PhysicalOp):
    def __init__(self, read_tasks):
        super().__init__("Read")
        self._remote = ray.remote(_read_task)
        self.input_queue = list(read_tasks)
        self.upstream_done = True

    def launch_one(self):
        fn = self.input_queue.pop(0)
        return self._track([self._remote.remote(fn)])


class MapPhysicalOp(PhysicalOp):
    def __init__(self, stages: list[MapStage]):
        names = "->".join(s.kind for s in stages)
        super().__init__(f"Map[{names}]")
        self._remote = ray.remote(_map_task)
        self._stages = stages

    def launch_one(self):
        block_ref = self.input_queue.pop(0)
        return self._track([self._remote.remote(self._stages, block_ref)])


class ActorPoolMapPhysicalOp(PhysicalOp):
    """map_batches over a pool of stateful actors: the fn (usually a
    class holding a model) is constructed once per actor; blocks route to
    the least-loaded actor. Reference:
    ``actor_pool_map_operator.py`` + ``ActorPoolStrategy``."""

    def __init__(self, fn, batch_format: str, fn_kwargs: dict, *,
                 pool_size: int, constructor_args: tuple = (),
                 constructor_kwargs: dict | None = None,
                 max_tasks_per_actor: int = 2):
        super().__init__(f"ActorPoolMap[{getattr(fn, '__name__', 'fn')}x{pool_size}]")
        self._fn = fn
        self._batch_format = batch_format
        self._fn_kwargs = fn_kwargs
        self._pool_size = pool_size
        self._ctor = (constructor_args, constructor_kwargs or {})
        self._max_per_actor = max_tasks_per_actor
        self._actors: list = []
        self._actor_load: dict[int, int] = {}  # actor index -> in-flight
        self._ref_to_actor: dict = {}

    def _ensure_pool(self) -> None:
        if self._actors:
            return
        cls = ray.remote(_MapWorker)
        args, kwargs = self._ctor
        self._actors = [cls.remote(self._fn, args, kwargs) for _ in range(self._pool_size)]
        self._actor_load = {i: 0 for i in range(self._pool_size)}

    def can_launch(self) -> bool:
        if not self.input_queue:
            return False
        if not self._actors:
            return True  # pool created on first launch
        return min(self._actor_load.values()) < self._max_per_actor

    def launch_one(self):
        self._ensure_pool()
        idx = min(self._actor_load, key=self._actor_load.get)
        block_ref = self.input_queue.pop(0)
        ref = self._actors[idx].apply.remote(self._batch_format, self._fn_kwargs, block_ref)
        self._actor_load[idx] += 1
        self._ref_to_actor[ref] = idx
        return self._track([ref])

    def on_complete(self, ref) -> None:
        idx = self._ref_to_actor.pop(ref, None)
        if idx is not None:
            self._actor_load[idx] -= 1
        super().on_complete(ref)

    def close(self) -> None:
        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        self._actors = []


class AllToAllPhysicalOp(PhysicalOp):
    """Barrier op: waits for the whole upstream, then one consolidation
    task emits num_out blocks."""

    def __init__(self, kind: str, *, num_out: int | None = None, seed=None,
                 sort_key: str = "", descending: bool = False):
        super().__init__(f"AllToAll[{kind}]")
        self._kind = kind
        self._num_out = num_out
        self._seed = seed
        self._sort_key = sort_key
        self._descending = descending
        self._launched = False

    def can_launch(self) -> bool:
        return self.upstream_done and not self._launched and bool(self.input_queue)

    def launch_one(self):
        blocks = list(self.input_queue)
        self.input_queue.clear()
        self._launched = True
        num_out = self._num_out or len(blocks) or 1
        remote = ray.remote(_consolidate_task).options(num_returns=num_out)
        refs = remote.remote(
            self._kind, num_out, self._seed, self._sort_key, self._descending, *blocks
        )
        if num_out == 1:
            refs = [refs]
        return self._track(list(refs))

    def done(self) -> bool:
        # also covers an empty upstream (nothing to consolidate)
        return self.upstream_done and not self.in_flight and not self.input_queue


class LimitPhysicalOp(PhysicalOp):
    """Driver-side streaming limit: truncates blocks until the row budget
    is spent, then drops the rest of the stream."""

    def __init__(self, limit: int):
        super().__init__(f"Limit[{limit}]")
        self._remaining = limit
        self._slice_remote = ray.remote(_slice_task)

    def can_launch(self) -> bool:
        # one in-flight slice at a time: each slice budget depends on the
        # rows consumed by the previous one
        return bool(self.input_queue) and not self.in_flight

    def launch_one(self):
        block_ref = self.input_queue.pop(0)
        if self._remaining <= 0:
            return []
        return self._track([self._slice_remote.remote(self._remaining, block_ref)])

    def on_complete(self, ref) -> None:
        block = ray.get(ref)
        self._remaining -= BlockAccessor.for_block(block).num_rows()
        super().on_complete(ref)
        if self._remaining <= 0:
            self.input_queue.clear()
            self.upstream_done = True


def _slice_task(limit: int, block):
    if block.num_rows <= limit:
        return block
    return block.slice(0, limit)


# ----------------------------------------------------------------- planning


def plan(last_op: L.LogicalOp) -> list[PhysicalOp]:
    """Lower the logical chain to physical ops, fusing adjacent maps."""
    ops: list[PhysicalOp] = []
    pending_stages: list[MapStage] = []

    def flush_maps():
        nonlocal pending_stages
        if pending_stages:
            ops.append(MapPhysicalOp(pending_stages))
            pending_stages = []

    for lop in last_op.chain():
        if isinstance(lop, L.Read):
            ops.append(ReadPhysicalOp(lop.read_tasks))
        elif isinstance(lop, L.MapBatches):
            if lop.compute is not None:
                # Actor-pool compute is a fusion barrier: the stateful fn
                # lives on dedicated actors, not fused into block tasks.
                flush_maps()
                ops.append(ActorPoolMapPhysicalOp(
                    lop.fn, lop.batch_format, lop.fn_kwargs,
                    pool_size=lop.compute.size,
                    constructor_args=lop.fn_constructor_args,
                    constructor_kwargs=lop.fn_constructor_kwargs,
                ))
            else:
                pending_stages.append(MapStage("batches", lop.fn, lop.batch_format, lop.fn_kwargs))
        elif isinstance(lop, L.MapRows):
            pending_stages.append(MapStage("rows", lop.fn))
        elif isinstance(lop, L.FlatMap):
            pending_stages.append(MapStage("flat", lop.fn))
        elif isinstance(lop, L.Filter):
            pending_stages.append(MapStage("filter", lop.fn))
        elif isinstance(lop, L.Repartition):
            flush_maps()
            ops.append(AllToAllPhysicalOp("repartition", num_out=lop.num_blocks))
        elif isinstance(lop, L.RandomShuffle):
            flush_maps()
            ops.append(AllToAllPhysicalOp("shuffle", seed=lop.seed))
        elif isinstance(lop, L.Sort):
            flush_maps()
            ops.append(AllToAllPhysicalOp("sort", sort_key=lop.key, descending=lop.descending))
        elif isinstance(lop, L.Limit):
            flush_maps()
            ops.append(LimitPhysicalOp(lop.limit))
        elif isinstance(lop, L.Union):
            raise NotImplementedError("union is handled at the Dataset level")
        else:
            raise ValueError(f"unknown logical op {lop}")
    flush_maps()
    return ops


# ---------------------------------------------------------------- executor


class StreamingExecutor:
    """Drives the physical op pipeline; yields output block refs as ready.

    Backpressure: at most ``max_in_flight`` tasks cluster-wide and
    ``per_op_concurrency`` per operator (reference: backpressure_policy/).
    """

    def __init__(self, ops: list[PhysicalOp], *, max_in_flight: int = 8,
                 per_op_concurrency: int = 4):
        self._ops = ops
        self._max_in_flight = max_in_flight
        self._per_op = per_op_concurrency

    def run(self) -> Iterator[Any]:
        try:
            yield from self._run_inner()
        finally:
            for op in self._ops:
                op.close()

    def _run_inner(self) -> Iterator[Any]:
        ops = self._ops
        last = ops[-1]
        while True:
            # 1. propagate completion flags + move outputs downstream
            for i, op in enumerate(ops):
                if i > 0:
                    upstream = ops[i - 1]
                    op.input_queue.extend(upstream.output_queue)
                    upstream.output_queue.clear()
                    op.upstream_done = upstream.done()
            while last.output_queue:
                yield last.output_queue.pop(0)
            if last.done():
                return

            # 2. poll in-flight tasks (small timeout so the loop stays live)
            all_refs = [r for op in ops for r in op.in_flight]
            progressed = False
            if all_refs:
                ready, _ = ray.wait(all_refs, num_returns=1, timeout=0.5)
                for ref in ready:
                    for op in ops:
                        if ref in op.in_flight:
                            op.on_complete(ref)
                            progressed = True
                            break

            # 3. launch new work, downstream ops first (finish-what-you-
            #    started, the reference's select_operator_to_run bias)
            total_in_flight = sum(len(op.in_flight) for op in ops)
            for op in reversed(ops):
                while (
                    op.can_launch()
                    and len(op.in_flight) < self._per_op
                    and total_in_flight < self._max_in_flight
                ):
                    launched = op.launch_one()
                    total_in_flight += len(launched)
                    progressed = True
            if not progressed and not all_refs:
                # nothing running and nothing launched: avoid a hot spin
                import time

                time.sleep(0.01)
