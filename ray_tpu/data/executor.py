"""Physical plan + streaming executor.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py:48``
(loop :233,285; ``select_operator_to_run`` in streaming_executor_state.py:531).
The shape is the same in miniature: physical operators with input/output
queues, a driver scheduling loop that moves completed blocks downstream
and launches new tasks under per-op concurrency and a global in-flight
cap (backpressure). Map chains are fused into one task per block
(the optimizer's operator-fusion rule).

All-to-all ops (shuffle/sort/repartition/groupby/join) run as a push-based
map-reduce partition exchange (reference ``_internal/planner/exchange/
push_based_shuffle_task_scheduler.py``): map tasks partition each upstream
block as it arrives (streaming — no barrier on the input side), reduce
tasks merge one partition each, so no single task ever holds the whole
dataset. Sort samples key ranges first (range partitioning); groupby and
join hash-partition on the key with a cross-process-stable hash.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator

import numpy as np

from ..core import api as ray
from . import logical as L
from .block import BlockAccessor, batch_to_block, build_block, concat_blocks

# ---------------------------------------------------------------- map stages


@dataclasses.dataclass
class MapStage:
    kind: str  # "batches" | "rows" | "flat" | "filter"
    fn: Callable
    batch_format: str = "numpy"
    fn_kwargs: dict = dataclasses.field(default_factory=dict)


def _apply_stages(block, stages: list[MapStage]):
    for st in stages:
        acc = BlockAccessor.for_block(block)
        if st.kind == "batches":
            batch = acc.to_batch(st.batch_format)
            block = batch_to_block(st.fn(batch, **st.fn_kwargs))
        elif st.kind == "rows":
            block = build_block([st.fn(r) for r in acc.iter_rows()])
        elif st.kind == "flat":
            out = []
            for r in acc.iter_rows():
                out.extend(st.fn(r))
            block = build_block(out)
        elif st.kind == "filter":
            block = build_block([r for r in acc.iter_rows() if st.fn(r)])
        else:
            raise ValueError(st.kind)
    return block


def _read_task(fn):
    block = fn()
    import pyarrow as pa

    if not isinstance(block, pa.Table):
        block = batch_to_block(block)
    return block


def _map_task(stages: list[MapStage], block):
    return _apply_stages(block, stages)


class _MapWorker:
    """Stateful map_batches worker (reference: actor-pool map operator —
    ``_internal/execution/operators/actor_pool_map_operator.py``). A class
    fn is constructed ONCE per actor (e.g. loads a model); plain callables
    pass through."""

    def __init__(self, fn, constructor_args: tuple, constructor_kwargs: dict):
        self.fn = fn(*constructor_args, **constructor_kwargs) if isinstance(fn, type) else fn

    def apply(self, batch_format: str, fn_kwargs: dict, block):
        return _apply_stages(
            block, [MapStage("batches", self.fn, batch_format, fn_kwargs)]
        )


# ------------------------------------------------------- exchange tasks


def _stable_hash_partition(block, key: str, num_out: int) -> np.ndarray:
    """Partition assignment by a hash that is STABLE across worker
    processes (Python's builtin hash is salted per process, which would
    scatter equal keys across partitions)."""
    import pandas as pd

    vals = block.column(key).to_pandas()
    return (pd.util.hash_array(np.asarray(vals)) % num_out).astype(np.int64)


def _exchange_map_task(kind: str, num_out: int, spec: dict, map_index: int, block):
    """Partition one upstream block into ``num_out`` parts (the map half
    of the exchange; reference ``exchange/shuffle_task_spec.py``)."""
    n = block.num_rows
    if n == 0:
        # A schema-less empty block (e.g. from_items([])) has no key
        # column to hash/range on — emit empty parts directly.
        assign = np.zeros(0, dtype=np.int64)
    elif kind == "shuffle":
        rng = np.random.default_rng((spec.get("seed") or 0) + map_index * 7919)
        assign = rng.integers(0, num_out, n)
    elif kind == "repartition":
        assign = (np.arange(n) + map_index) % num_out  # row round-robin
    elif kind == "sort":
        col = block.column(spec["sort_key"]).to_numpy(zero_copy_only=False)
        assign = np.searchsorted(np.asarray(spec["boundaries"]), col, side="right")
    elif kind in ("groupby", "join"):
        assign = _stable_hash_partition(block, spec["key"], num_out)
    else:
        raise ValueError(kind)
    parts = []
    for i in range(num_out):
        part = block.take(np.nonzero(assign == i)[0])
        if kind == "groupby" and spec.get("aggs") and part.schema.names:
            part = _partial_aggregate(part, spec)  # map-side combine
        parts.append(part)
    return tuple(parts) if num_out > 1 else parts[0]


# Aggregations decompose into (map-side partial, reduce-side merge) so the
# reduce only sees one partial row per key per map task (reference
# AggregateFn accumulate/merge/finalize).
_AGG_PARTIAL = {"count": "count", "sum": "sum", "min": "min", "max": "max"}


def _partial_aggregate(part, spec: dict):
    key = spec["key"]
    aggs = []
    for col, op in spec["aggs"]:
        if op == "mean":
            aggs.append((col, "sum"))
            aggs.append((col, "count"))
        else:
            aggs.append((col if op != "count" else key, _AGG_PARTIAL[op]))
    return part.group_by(key).aggregate(_dedupe(aggs))


def _dedupe(aggs: list[tuple]) -> list[tuple]:
    seen, out = set(), []
    for a in aggs:
        if a not in seen:
            seen.add(a)
            out.append(a)
    return out


def _exchange_reduce_task(kind: str, spec: dict, part_index: int, n_left: int, *parts):
    """Merge one partition's pieces from every map task (the reduce half).
    For joins, ``parts[:n_left]`` are the left side's pieces and the rest
    the right side's (same hash partition on both)."""
    left_parts = list(parts[:n_left])
    right_parts = list(parts[n_left:])
    if not left_parts:
        # Join whose left upstream produced zero blocks (n_left == 0): an
        # empty placeholder; the join branch below synthesizes the key-only
        # empty left table (left-only columns are unknowable and absent).
        left_parts = [_concat_keep_schema(right_parts).slice(0, 0).select([])]
    merged = _concat_keep_schema(left_parts)
    if merged.num_rows == 0 and not merged.schema.names and kind != "join":
        return merged  # schema-less empty partition: nothing to sort/merge
    if kind == "shuffle":
        rng = np.random.default_rng((spec.get("seed") or 0) ^ (part_index + 1))
        return merged.take(rng.permutation(merged.num_rows))
    if kind == "sort":
        order = "descending" if spec.get("descending") else "ascending"
        return merged.sort_by([(spec["sort_key"], order)])
    if kind == "groupby":
        return _final_aggregate(merged, spec)
    if kind == "join":
        right = _concat_keep_schema(right_parts or [merged.slice(0, 0)])
        # A side fed only schema-less empty blocks lacks the key columns;
        # substitute a key-only empty table so the join stays executable.
        keys = spec["key"] if isinstance(spec["key"], list) else [spec["key"]]

        def _has_keys(t):
            return set(keys) <= set(t.schema.names)

        if merged.num_rows == 0 and not _has_keys(merged):
            if not _has_keys(right):
                return merged  # both sides schema-less empty
            merged = right.select(keys).slice(0, 0)
        if right.num_rows == 0 and not _has_keys(right):
            right = merged.select(keys).slice(0, 0)
        return merged.join(right, keys=spec["key"], join_type=spec.get("join_type", "inner"))
    return merged  # repartition


def _concat_keep_schema(parts: list):
    """concat that keeps the schema when every part is empty (an empty
    hash/range partition must stay sortable/groupable downstream)."""
    non_empty = [p for p in parts if p.num_rows]
    if not non_empty:
        return parts[0]
    return concat_blocks(non_empty)


def _final_aggregate(merged, spec: dict):
    import pyarrow as pa

    key = spec["key"]
    if spec.get("map_groups_fn") is not None:
        fn = spec["map_groups_fn"]
        acc = BlockAccessor.for_block(merged)
        groups: dict = {}
        for row in acc.iter_rows():
            groups.setdefault(row[key], []).append(row)
        out_rows = []
        for _, rows in sorted(groups.items(), key=lambda kv: str(kv[0])):
            result = fn(_rows_to_batch(rows))
            out_rows.extend(_batch_to_rows(result))
        return build_block(out_rows)
    # Merge map-side partials: count -> sum of counts, sum -> sum of sums,
    # min/max idempotent, mean -> sum/count finalize.
    merges = []
    for col, op in spec["aggs"]:
        if op == "count":
            merges.append((f"{key}_count", "sum"))
        elif op == "sum":
            merges.append((f"{col}_sum", "sum"))
        elif op == "min":
            merges.append((f"{col}_min", "min"))
        elif op == "max":
            merges.append((f"{col}_max", "max"))
        elif op == "mean":
            merges.append((f"{col}_sum", "sum"))
            merges.append((f"{col}_count", "sum"))
    table = merged.group_by(key).aggregate(_dedupe(merges))
    # Rename/finalize to the reference's output names: op(col).
    cols = {key: table.column(key)}
    for col, op in spec["aggs"]:
        if op == "count":
            cols["count()"] = table.column(f"{key}_count_sum")
        elif op == "mean":
            s = table.column(f"{col}_sum_sum").to_numpy(zero_copy_only=False)
            c = table.column(f"{col}_count_sum").to_numpy(zero_copy_only=False)
            cols[f"mean({col})"] = pa.array(s / np.maximum(c, 1))
        else:
            cols[f"{op}({col})"] = table.column(f"{col}_{op}_{'sum' if op == 'sum' else op}")
    return pa.table(cols)


def _rows_to_batch(rows: list[dict]) -> dict:
    keys = rows[0].keys()
    return {k: np.asarray([r[k] for r in rows]) for k in keys}


def _batch_to_rows(result) -> list[dict]:
    if isinstance(result, dict):
        keys = list(result)
        n = len(next(iter(result.values()))) if result else 0
        return [{k: result[k][i] for k in keys} for i in range(n)]
    if isinstance(result, list):
        return result
    raise TypeError(f"map_groups fn must return a dict batch or list of rows, got {type(result)}")


def _sample_task(key: str, block):
    """Sort pre-pass: sample up to 100 key values from a block."""
    col = block.column(key).to_numpy(zero_copy_only=False)
    if len(col) > 100:
        idx = np.random.default_rng(0).choice(len(col), 100, replace=False)
        col = col[idx]
    return np.asarray(col)


# ------------------------------------------------------------- physical ops


class PhysicalOp:
    """Blocks are emitted in input order (completion order is buffered
    through a per-op reorder window), so downstream semantics — take(),
    zip-like joins, batch determinism — match the logical plan order."""

    def __init__(self, name: str):
        self.name = name
        self.input_queue: list = []  # upstream block refs
        self.in_flight: dict = {}  # ref -> seq
        self.output_queue: list = []
        self.upstream_done = False
        self._next_seq = 0
        self._emit_seq = 0
        self._completed: dict[int, Any] = {}
        # per-op execution stats (reference data/_internal/stats.py):
        # wall = task submit->complete (includes queue + remote exec)
        self.stats = {"tasks": 0, "blocks_out": 0, "wall_s": 0.0}
        self._launched_at: dict = {}

    def done(self) -> bool:
        return self.upstream_done and not self.input_queue and not self.in_flight

    def can_launch(self) -> bool:
        return bool(self.input_queue)

    def launch_one(self) -> list:
        raise NotImplementedError

    def _track(self, refs: list) -> list:
        import time as _time

        now = _time.monotonic()
        for r in refs:
            self.in_flight[r] = self._next_seq
            self._next_seq += 1
            self._launched_at[r] = now
        return refs

    def on_complete(self, ref) -> None:
        import time as _time

        t0 = self._launched_at.pop(ref, None)
        if t0 is not None:
            self.stats["wall_s"] += _time.monotonic() - t0
            self.stats["tasks"] += 1
            self.stats["blocks_out"] += 1
        seq = self.in_flight.pop(ref)
        self._completed[seq] = ref
        while self._emit_seq in self._completed:
            self.output_queue.append(self._completed.pop(self._emit_seq))
            self._emit_seq += 1

    def close(self) -> None:
        """Release operator resources (actor pools) at stream end."""


class ReadPhysicalOp(PhysicalOp):
    def __init__(self, read_tasks):
        super().__init__("Read")
        self._remote = ray.remote(_read_task)
        self.input_queue = list(read_tasks)
        self.upstream_done = True

    def launch_one(self):
        fn = self.input_queue.pop(0)
        return self._track([self._remote.remote(fn)])


class MapPhysicalOp(PhysicalOp):
    def __init__(self, stages: list[MapStage]):
        names = "->".join(s.kind for s in stages)
        super().__init__(f"Map[{names}]")
        self._remote = ray.remote(_map_task)
        self._stages = stages

    def launch_one(self):
        block_ref = self.input_queue.pop(0)
        return self._track([self._remote.remote(self._stages, block_ref)])


class ActorPoolMapPhysicalOp(PhysicalOp):
    """map_batches over a pool of stateful actors: the fn (usually a
    class holding a model) is constructed once per actor; blocks route to
    the least-loaded actor. Reference:
    ``actor_pool_map_operator.py`` + ``ActorPoolStrategy``."""

    def __init__(self, fn, batch_format: str, fn_kwargs: dict, *,
                 pool_size: int, constructor_args: tuple = (),
                 constructor_kwargs: dict | None = None,
                 ray_actor_options: dict | None = None,
                 max_tasks_per_actor: int = 2):
        super().__init__(f"ActorPoolMap[{getattr(fn, '__name__', 'fn')}x{pool_size}]")
        self._fn = fn
        self._batch_format = batch_format
        self._fn_kwargs = fn_kwargs
        self._pool_size = pool_size
        self._actor_options = ray_actor_options or {}
        self._ctor = (constructor_args, constructor_kwargs or {})
        self._max_per_actor = max_tasks_per_actor
        self._actors: list = []
        self._actor_load: dict[int, int] = {}  # actor index -> in-flight
        self._ref_to_actor: dict = {}

    def _ensure_pool(self) -> None:
        if self._actors:
            return
        cls = ray.remote(_MapWorker)
        if self._actor_options:
            cls = cls.options(**self._actor_options)
        args, kwargs = self._ctor
        self._actors = [cls.remote(self._fn, args, kwargs) for _ in range(self._pool_size)]
        self._actor_load = {i: 0 for i in range(self._pool_size)}

    def can_launch(self) -> bool:
        if not self.input_queue:
            return False
        if not self._actors:
            return True  # pool created on first launch
        return min(self._actor_load.values()) < self._max_per_actor

    def launch_one(self):
        self._ensure_pool()
        idx = min(self._actor_load, key=self._actor_load.get)
        block_ref = self.input_queue.pop(0)
        ref = self._actors[idx].apply.remote(self._batch_format, self._fn_kwargs, block_ref)
        self._actor_load[idx] += 1
        self._ref_to_actor[ref] = idx
        return self._track([ref])

    def on_complete(self, ref) -> None:
        idx = self._ref_to_actor.pop(ref, None)
        if idx is not None:
            self._actor_load[idx] -= 1
        super().on_complete(ref)

    def close(self) -> None:
        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        self._actors = []


class ExchangePhysicalOp(PhysicalOp):
    """Push-based map-reduce partition exchange behind every all-to-all op
    (reference ``push_based_shuffle_task_scheduler.py``).

    Map tasks launch as upstream blocks ARRIVE (no input barrier); each
    partitions its block into ``num_out`` pieces. Once the upstream and
    all maps finish, ``num_out`` reduce tasks each merge one partition —
    so peak per-task memory is one partition, not the dataset. Sort runs
    a sampling pre-pass to pick range boundaries; join hash-partitions
    the (pre-materialized) right side through the same maps."""

    def __init__(self, kind: str, *, num_out: int | None = None, seed=None,
                 sort_key: str = "", descending: bool = False, key: str = "",
                 aggs: list | None = None, map_groups_fn=None,
                 right_refs: list | None = None, join_type: str = "inner"):
        from ..core.config import get_config

        super().__init__(f"Exchange[{kind}]")
        self._kind = kind
        self._num_out = num_out or get_config().data_exchange_partitions
        self._spec = {
            "seed": seed, "sort_key": sort_key, "descending": descending,
            "key": key, "aggs": aggs, "map_groups_fn": map_groups_fn,
            "join_type": join_type,
        }
        self._map_remote = ray.remote(_exchange_map_task).options(num_returns=self._num_out) \
            if self._num_out > 1 else ray.remote(_exchange_map_task)
        self._reduce_remote = ray.remote(_exchange_reduce_task)
        self._sample_remote = ray.remote(_sample_task)
        self._internal: dict = {}           # ref -> ("sample"|"map", ...)
        self._pending_sample: list = []     # block refs awaiting boundaries (sort)
        self._samples: list = []
        self._boundaries_ready = kind != "sort"
        self._map_outputs: list[list] = []  # per map: [num_out refs]
        self._map_index = 0
        self._maps_in_flight = 0
        self._right_refs = list(right_refs or [])
        self._right_outputs: list[list] = []
        self._reduces_launched = 0

    # Upstream blocks stack in input_queue; right-side blocks are seeded
    # into the map queue too (tagged).
    def can_launch(self) -> bool:
        if self._kind == "sort" and not self._boundaries_ready:
            # Sampling phase: one sample task per arriving block.
            return bool(self.input_queue) or self._maybe_finish_sampling()
        if self.input_queue or self._right_refs:
            return True
        return self._can_reduce()

    def _maybe_finish_sampling(self) -> bool:
        if (self.upstream_done and not self.input_queue
                and not any(k[0] == "sample" for k in self._internal.values())
                and not self._boundaries_ready):
            # All samples in: compute range boundaries on the driver.
            # Order statistics (sort + index at quantile positions) rather
            # than np.quantile, so string and other non-numeric but
            # comparable sort keys partition correctly too.
            vals = np.concatenate(self._samples) if self._samples else np.array([0.0])
            if vals.size == 0:  # blocks existed but every one was empty
                vals = np.array([0.0])
            vals = np.sort(vals)
            last = len(vals) - 1
            self._spec["boundaries"] = [
                vals[min(last, int(round((i + 1) / self._num_out * last)))]
                for i in range(self._num_out - 1)
            ]
            self._boundaries_ready = True
            # blocks return to the map queue
            self.input_queue = self._pending_sample + self.input_queue
            self._pending_sample = []
            return bool(self.input_queue)
        return False

    def _can_reduce(self) -> bool:
        return (self.upstream_done and not self.input_queue and not self._right_refs
                and self._boundaries_ready and self._maps_in_flight == 0
                and self._reduces_launched < self._num_out
                and bool(self._map_outputs or self._right_outputs))

    def launch_one(self):
        if self._kind == "sort" and not self._boundaries_ready:
            if not self.input_queue:
                return []  # _maybe_finish_sampling flipped the phase
            block_ref = self.input_queue.pop(0)
            self._pending_sample.append(block_ref)
            ref = self._sample_remote.remote(self._spec["sort_key"], block_ref)
            self._internal[ref] = ("sample",)
            self.in_flight[ref] = None
            return [ref]
        if self.input_queue or self._right_refs:
            side = "left" if self.input_queue else "right"
            block_ref = (self.input_queue.pop(0) if side == "left"
                         else self._right_refs.pop(0))
            refs = self._map_remote.remote(
                self._kind, self._num_out, self._spec, self._map_index, block_ref)
            self._map_index += 1
            if self._num_out == 1:
                refs = [refs]
            refs = list(refs)
            out_list = self._map_outputs if side == "left" else self._right_outputs
            out_list.append(refs)
            self._maps_in_flight += 1
            # Track ONE ref per map for completion accounting (siblings of
            # a multi-return task complete together).
            self._internal[refs[0]] = ("map",)
            self.in_flight[refs[0]] = None
            return [refs[0]]
        if self._can_reduce():
            i = self._reduces_launched
            self._reduces_launched += 1
            # Descending sort: partition 0 holds the SMALLEST range — emit
            # partitions in reverse so the global stream is ordered.
            if self._kind == "sort" and self._spec.get("descending"):
                i = self._num_out - 1 - i
            left = [m[i] for m in self._map_outputs]
            right = [m[i] for m in self._right_outputs]
            spec = {k: v for k, v in self._spec.items() if k != "map_groups_fn"}
            spec["map_groups_fn"] = self._spec["map_groups_fn"]
            ref = self._reduce_remote.remote(
                self._kind, spec, i, len(left), *(left + right))
            return self._track([ref])
        return []

    def on_complete(self, ref) -> None:
        tag = self._internal.pop(ref, None)
        if tag is None:
            super().on_complete(ref)  # a reduce: ordered output emission
            return
        self.in_flight.pop(ref, None)
        if tag[0] == "sample":
            self._samples.append(ray.get(ref))
        else:  # map
            self._maps_in_flight -= 1

    def done(self) -> bool:
        if not (self.upstream_done and not self.input_queue and not self.in_flight
                and not self._right_refs):
            return False
        if not self._map_outputs and not self._right_outputs:
            return True  # empty upstream: nothing to exchange
        return self._reduces_launched >= self._num_out


class LimitPhysicalOp(PhysicalOp):
    """Driver-side streaming limit: truncates blocks until the row budget
    is spent, then drops the rest of the stream."""

    def __init__(self, limit: int):
        super().__init__(f"Limit[{limit}]")
        self._remaining = limit
        self._slice_remote = ray.remote(_slice_task)

    def can_launch(self) -> bool:
        # one in-flight slice at a time: each slice budget depends on the
        # rows consumed by the previous one
        return bool(self.input_queue) and not self.in_flight

    def launch_one(self):
        block_ref = self.input_queue.pop(0)
        if self._remaining <= 0:
            return []
        return self._track([self._slice_remote.remote(self._remaining, block_ref)])

    def on_complete(self, ref) -> None:
        block = ray.get(ref)
        self._remaining -= BlockAccessor.for_block(block).num_rows()
        super().on_complete(ref)
        if self._remaining <= 0:
            self.input_queue.clear()
            self.upstream_done = True


def _slice_task(limit: int, block):
    if block.num_rows <= limit:
        return block
    return block.slice(0, limit)


# ----------------------------------------------------------------- planning


def plan(last_op: L.LogicalOp) -> list[PhysicalOp]:
    """Lower the logical chain to physical ops, fusing adjacent maps."""
    ops: list[PhysicalOp] = []
    pending_stages: list[MapStage] = []

    def flush_maps():
        nonlocal pending_stages
        if pending_stages:
            ops.append(MapPhysicalOp(pending_stages))
            pending_stages = []

    for lop in last_op.chain():
        if isinstance(lop, L.Read):
            ops.append(ReadPhysicalOp(lop.read_tasks))
        elif isinstance(lop, L.MapBatches):
            if lop.compute is not None:
                # Actor-pool compute is a fusion barrier: the stateful fn
                # lives on dedicated actors, not fused into block tasks.
                flush_maps()
                ops.append(ActorPoolMapPhysicalOp(
                    lop.fn, lop.batch_format, lop.fn_kwargs,
                    pool_size=lop.compute.size,
                    constructor_args=lop.fn_constructor_args,
                    constructor_kwargs=lop.fn_constructor_kwargs,
                    ray_actor_options=lop.ray_actor_options,
                ))
            else:
                pending_stages.append(MapStage("batches", lop.fn, lop.batch_format, lop.fn_kwargs))
        elif isinstance(lop, L.MapRows):
            pending_stages.append(MapStage("rows", lop.fn))
        elif isinstance(lop, L.FlatMap):
            pending_stages.append(MapStage("flat", lop.fn))
        elif isinstance(lop, L.Filter):
            pending_stages.append(MapStage("filter", lop.fn))
        elif isinstance(lop, L.Repartition):
            flush_maps()
            ops.append(ExchangePhysicalOp("repartition", num_out=lop.num_blocks))
        elif isinstance(lop, L.RandomShuffle):
            flush_maps()
            ops.append(ExchangePhysicalOp("shuffle", seed=lop.seed))
        elif isinstance(lop, L.Sort):
            flush_maps()
            ops.append(ExchangePhysicalOp("sort", sort_key=lop.key, descending=lop.descending))
        elif isinstance(lop, L.GroupByAggregate):
            flush_maps()
            ops.append(ExchangePhysicalOp(
                "groupby", num_out=lop.num_out, key=lop.key, aggs=lop.aggs,
                map_groups_fn=lop.map_groups_fn))
        elif isinstance(lop, L.Join):
            flush_maps()
            ops.append(ExchangePhysicalOp(
                "join", num_out=lop.num_out, key=lop.key,
                right_refs=lop.right_refs, join_type=lop.join_type))
        elif isinstance(lop, L.Limit):
            flush_maps()
            ops.append(LimitPhysicalOp(lop.limit))
        elif isinstance(lop, L.Union):
            raise NotImplementedError("union is handled at the Dataset level")
        else:
            raise ValueError(f"unknown logical op {lop}")
    flush_maps()
    return ops


# ---------------------------------------------------------------- executor


class StreamingExecutor:
    """Drives the physical op pipeline; yields output block refs as ready.

    Backpressure: at most ``max_in_flight`` tasks cluster-wide and
    ``per_op_concurrency`` per operator (reference: backpressure_policy/).
    """

    def __init__(self, ops: list[PhysicalOp], *, max_in_flight: int | None = None,
                 per_op_concurrency: int | None = None):
        from ..core.config import get_config

        cfg = get_config()
        self._ops = ops
        self._max_in_flight = max_in_flight or cfg.data_max_in_flight_tasks
        self._per_op = per_op_concurrency or cfg.data_per_op_concurrency

    def run(self) -> Iterator[Any]:
        try:
            yield from self._run_inner()
        finally:
            for op in self._ops:
                op.close()

    def _run_inner(self) -> Iterator[Any]:
        ops = self._ops
        last = ops[-1]
        while True:
            # 1. propagate completion flags + move outputs downstream
            for i, op in enumerate(ops):
                if i > 0:
                    upstream = ops[i - 1]
                    op.input_queue.extend(upstream.output_queue)
                    upstream.output_queue.clear()
                    op.upstream_done = upstream.done()
            while last.output_queue:
                yield last.output_queue.pop(0)
            if last.done():
                return

            # 2. poll in-flight tasks (small timeout so the loop stays live)
            all_refs = [r for op in ops for r in op.in_flight]
            progressed = False
            if all_refs:
                ready, _ = ray.wait(all_refs, num_returns=1, timeout=0.5)
                for ref in ready:
                    for op in ops:
                        if ref in op.in_flight:
                            op.on_complete(ref)
                            progressed = True
                            break

            # 3. launch new work, downstream ops first (finish-what-you-
            #    started, the reference's select_operator_to_run bias)
            total_in_flight = sum(len(op.in_flight) for op in ops)
            for op in reversed(ops):
                while (
                    op.can_launch()
                    and len(op.in_flight) < self._per_op
                    and total_in_flight < self._max_in_flight
                ):
                    launched = op.launch_one()
                    total_in_flight += len(launched)
                    progressed = True
            if not progressed and not all_refs:
                # nothing running and nothing launched: avoid a hot spin
                import time

                time.sleep(0.01)
