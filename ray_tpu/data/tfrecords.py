"""TFRecord datasource: read/write without a TensorFlow dependency.

Reference: ``python/ray/data/_internal/datasource/tfrecords_datasource.py``
(which parses via ``tf.train.Example``). TPU ingest commonly arrives as
TFRecord shards; this module implements the container format and a
minimal ``tf.train.Example`` protobuf codec natively:

  * TFRecord framing: ``uint64 length | uint32 masked_crc(length) |
    payload | uint32 masked_crc(payload)`` with CRC32C (Castagnoli)
    masked per the TF spec (rot15 + 0xa282ead8).
  * Example wire format: ``Example{features: Features{feature:
    map<string, Feature>}}``; ``Feature`` is a oneof of bytes_list /
    float_list / int64_list. Scalars flatten on read (list length 1 ->
    value), arrays stay lists.

Readers accept pyarrow.fs URIs like every other datasource.
"""

from __future__ import annotations

import struct
from typing import Callable, Iterator

# ------------------------------------------------------------------ crc32c

_CRC_TABLE: list[int] | None = None


def _crc32c_table() -> list[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------- record framing


def read_records(stream) -> Iterator[bytes]:
    """Yield raw record payloads; validates lengths (CRC checked on the
    header so corrupt shards fail fast, payload CRC skipped for speed —
    the reference's tf.io behavior with check_integrity off)."""
    while True:
        header = stream.read(12)
        if not header:
            return
        if len(header) < 12:
            raise ValueError("truncated TFRecord header")
        (length,) = struct.unpack("<Q", header[:8])
        (len_crc,) = struct.unpack("<I", header[8:])
        if len_crc != _masked_crc(header[:8]):
            raise ValueError("TFRecord length CRC mismatch (corrupt shard?)")
        payload = stream.read(length)
        if len(payload) < length:
            raise ValueError("truncated TFRecord payload")
        stream.read(4)  # payload crc (unchecked)
        yield payload


def write_record(stream, payload: bytes) -> None:
    header = struct.pack("<Q", len(payload))
    stream.write(header)
    stream.write(struct.pack("<I", _masked_crc(header)))
    stream.write(payload)
    stream.write(struct.pack("<I", _masked_crc(payload)))


# ------------------------------------------------- tf.train.Example codec

_WIRE_VARINT, _WIRE_I64, _WIRE_LEN, _WIRE_I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _skip_field(buf: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        return _read_varint(buf, pos)[1]
    if wire == _WIRE_I64:
        return pos + 8
    if wire == _WIRE_LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire == _WIRE_I32:
        return pos + 4
    raise ValueError(f"unknown wire type {wire}")


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, bytes]]:
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_LEN:
            n, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + n]
            pos += n
        else:
            start = pos
            pos = _skip_field(buf, pos, wire)
            yield field, wire, buf[start:pos]


def _parse_feature(buf: bytes):
    # Feature: oneof { bytes_list=1, float_list=2, int64_list=3 }
    for field, _, payload in _iter_fields(buf):
        if field == 1:    # BytesList{value: repeated bytes = 1}
            return [v for f, _, v in _iter_fields(payload) if f == 1]
        if field == 2:    # FloatList{value: repeated float = 1, packed}
            out: list[float] = []
            for f, wire, v in _iter_fields(payload):
                if f != 1:
                    continue
                if wire == _WIRE_LEN:  # packed
                    out.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    out.extend(struct.unpack("<f", v))
            return out
        if field == 3:    # Int64List{value: repeated int64 = 1, varint}
            out = []
            for f, wire, v in _iter_fields(payload):
                if f != 1:
                    continue
                if wire == _WIRE_LEN:  # packed varints
                    p = 0
                    while p < len(v):
                        n, p = _read_varint(v, p)
                        out.append(n - (1 << 64) if n >= 1 << 63 else n)
                else:
                    n = _read_varint(v, 0)[0]
                    out.append(n - (1 << 64) if n >= 1 << 63 else n)
            return out
    return []


def parse_example(payload: bytes) -> dict:
    """tf.train.Example bytes -> {name: scalar or list} row."""
    row: dict = {}
    for field, _, features in _iter_fields(payload):
        if field != 1:  # Example{features=1}
            continue
        for f2, _, entry in _iter_fields(features):
            if f2 != 1:  # Features{feature map entry=1}
                continue
            name = b""
            value = []
            for mf, _, mv in _iter_fields(entry):
                if mf == 1:
                    name = mv
                elif mf == 2:
                    value = _parse_feature(mv)
            if len(value) == 1:
                value = value[0]
            row[name.decode()] = value
    return row


def _encode_feature(values) -> bytes:
    inner = bytearray()
    if values and isinstance(values[0], bytes):
        body = bytearray()
        for v in values:
            body.append((1 << 3) | _WIRE_LEN)
            _write_varint(body, len(v))
            body += v
        field = 1
    elif values and isinstance(values[0], float):
        body = bytearray([(1 << 3) | _WIRE_LEN])
        packed = struct.pack(f"<{len(values)}f", *values)
        _write_varint(body, len(packed))
        body += packed
        field = 2
    else:
        body = bytearray([(1 << 3) | _WIRE_LEN])
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & ((1 << 64) - 1))
        _write_varint(body, len(packed))
        body += packed
        field = 3
    inner.append((field << 3) | _WIRE_LEN)
    _write_varint(inner, len(body))
    inner += body
    return bytes(inner)


def encode_example(row: dict) -> bytes:
    """{name: value} row -> tf.train.Example bytes."""
    features = bytearray()
    for name, value in row.items():
        if hasattr(value, "tolist"):
            value = value.tolist()
        values = value if isinstance(value, list) else [value]
        if values and isinstance(values[0], str):
            values = [v.encode() for v in values]
        key = name.encode()
        feat = _encode_feature(values)
        entry = bytearray([(1 << 3) | _WIRE_LEN])
        _write_varint(entry, len(key))
        entry += key
        entry.append((2 << 3) | _WIRE_LEN)
        _write_varint(entry, len(feat))
        entry += feat
        m = bytearray([(1 << 3) | _WIRE_LEN])
        _write_varint(m, len(entry))
        m += entry
        features += m
    out = bytearray([(1 << 3) | _WIRE_LEN])
    _write_varint(out, len(features))
    out += features
    return bytes(out)


# ---------------------------------------------------------------- read tasks


def tfrecords_tasks(paths) -> list[Callable]:
    """One read task per shard file (the reference's file-parallel split)."""
    from . import datasource as ds

    def make(fs, path):
        def task():
            import pyarrow as pa

            rows: list[dict] = []
            with fs.open_input_stream(path) as f:
                for payload in read_records(f):
                    rows.append(parse_example(payload))
            cols: dict[str, list] = {}
            for r in rows:
                for k in r:
                    cols.setdefault(k, [])
            for r in rows:
                for k, col in cols.items():
                    col.append(r.get(k))
            return pa.table(cols) if cols else pa.table({})
        return task

    return [make(fs, path) for fs, path in ds._expand_paths(paths)]
