"""ray_tpu.data: streaming, block-based distributed datasets.

Reference: ``python/ray/data/`` (SURVEY.md §2.3, §3.6): lazy logical
plans, operator fusion, a backpressured streaming executor over object-
store blocks, and train-worker stream splits. TPU-relevant surface:
``DataIterator.to_device_batches`` double-buffers host→HBM transfers.
"""

from .block import BlockAccessor
from .dataset import (
    ActorPoolStrategy,
    Dataset,
    GroupedData,
    MaterializedDataset,
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004
    read_avro,
    read_binary_files,
    read_images,
    read_tfrecords,
    read_webdataset,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)
from .iterator import DataIterator
from . import preprocessors

__all__ = [
    "preprocessors",
    "ActorPoolStrategy",
    "BlockAccessor",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "MaterializedDataset",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_avro",
    "read_binary_files",
    "read_images",
    "read_tfrecords",
    "read_webdataset",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
]
