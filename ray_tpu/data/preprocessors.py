"""Dataset preprocessors: fit statistics once, transform anywhere.

Equivalent of the reference's ``python/ray/data/preprocessors/`` —
``Preprocessor`` (fit/transform/transform_batch contract,
``preprocessor.py``), scalers (``scaler.py``), encoders
(``encoder.py``), imputer (``imputer.py``), concatenator
(``concatenator.py``), chain (``chain.py``). TPU-shaped differences:
fitting streams ONE pass over the dataset accumulating sufficient
statistics host-side (datasets are token/tensor streams, not pandas
frames), and transforms are numpy ``map_batches`` fns so they fuse into
the streaming executor like any other map stage and feed
``iter_batches`` -> ``jax.device_put`` directly.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    """Base contract: ``fit(ds)`` computes state, ``transform(ds)`` adds
    a map stage, ``transform_batch(batch)`` applies to one numpy-dict
    batch (serving-time single-record path)."""

    _is_fittable = True

    def __init__(self):
        self.stats_: dict[str, Any] = {}
        self._fitted = False

    # -------------------------------------------------------------- public
    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        self._check_fitted()
        return ds.map_batches(self.transform_batch, batch_format="numpy")

    def transform_batch(self, batch: dict) -> dict:
        self._check_fitted()
        return self._transform_numpy(dict(batch))

    def _check_fitted(self) -> None:
        if self._is_fittable and not self._fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit before transform")

    # ------------------------------------------------------------ override
    def _fit(self, ds) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _transform_numpy(self, batch: dict) -> dict:  # pragma: no cover
        raise NotImplementedError


def _iter_np_batches(ds) -> Iterable[dict]:
    for batch in ds.iter_batches(batch_size=4096, batch_format="numpy"):
        yield batch


class StandardScaler(Preprocessor):
    """Column-wise (x - mean) / std, std 0 -> 1 (ref scaler.py)."""

    def __init__(self, columns: list[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds) -> None:
        n = 0
        s = {c: 0.0 for c in self.columns}
        sq = {c: 0.0 for c in self.columns}
        for batch in _iter_np_batches(ds):
            for c in self.columns:
                col = np.asarray(batch[c], np.float64)
                s[c] += float(col.sum())
                sq[c] += float((col ** 2).sum())
            n += len(next(iter(batch.values())))
        for c in self.columns:
            mean = s[c] / max(n, 1)
            var = max(sq[c] / max(n, 1) - mean ** 2, 0.0)
            std = var ** 0.5
            self.stats_[f"mean({c})"] = mean
            self.stats_[f"std({c})"] = std if std > 0 else 1.0

    def _transform_numpy(self, batch: dict) -> dict:
        for c in self.columns:
            batch[c] = ((np.asarray(batch[c], np.float64)
                         - self.stats_[f"mean({c})"])
                        / self.stats_[f"std({c})"]).astype(np.float32)
        return batch


class MinMaxScaler(Preprocessor):
    """Column-wise (x - min) / (max - min), degenerate range -> 0."""

    def __init__(self, columns: list[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds) -> None:
        lo = {c: np.inf for c in self.columns}
        hi = {c: -np.inf for c in self.columns}
        for batch in _iter_np_batches(ds):
            for c in self.columns:
                col = np.asarray(batch[c], np.float64)
                lo[c] = min(lo[c], float(col.min()))
                hi[c] = max(hi[c], float(col.max()))
        for c in self.columns:
            self.stats_[f"min({c})"] = lo[c]
            self.stats_[f"max({c})"] = hi[c]

    def _transform_numpy(self, batch: dict) -> dict:
        for c in self.columns:
            lo = self.stats_[f"min({c})"]
            span = self.stats_[f"max({c})"] - lo
            col = np.asarray(batch[c], np.float64)
            batch[c] = (np.zeros_like(col, np.float32) if span == 0
                        else ((col - lo) / span).astype(np.float32))
        return batch


class LabelEncoder(Preprocessor):
    """String/any labels -> contiguous int ids (sorted order; unseen
    labels at transform raise, matching the reference)."""

    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column

    def _fit(self, ds) -> None:
        values: set = set()
        for batch in _iter_np_batches(ds):
            values.update(np.asarray(batch[self.label_column]).tolist())
        ordered = sorted(values, key=lambda v: (str(type(v)), v))
        self.stats_[f"unique_values({self.label_column})"] = {
            v: i for i, v in enumerate(ordered)}

    def _transform_numpy(self, batch: dict) -> dict:
        mapping = self.stats_[f"unique_values({self.label_column})"]
        col = np.asarray(batch[self.label_column]).tolist()
        try:
            batch[self.label_column] = np.asarray(
                [mapping[v] for v in col], np.int64)
        except KeyError as e:
            raise ValueError(
                f"label {e} not seen during fit for "
                f"{self.label_column!r}") from None
        return batch

    def inverse_transform_batch(self, batch: dict) -> dict:
        self._check_fitted()
        mapping = self.stats_[f"unique_values({self.label_column})"]
        inverse = {i: v for v, i in mapping.items()}
        batch = dict(batch)
        batch[self.label_column] = np.asarray(
            [inverse[int(i)] for i in np.asarray(batch[self.label_column])])
        return batch


class OneHotEncoder(Preprocessor):
    """Each categorical column -> one 0/1 column per seen value, named
    ``{col}_{value}``; the source column is dropped. Unseen values
    one-hot to all zeros (the reference's handle-unknown behavior)."""

    def __init__(self, columns: list[str]):
        super().__init__()
        self.columns = list(columns)

    def _fit(self, ds) -> None:
        values: dict[str, set] = {c: set() for c in self.columns}
        for batch in _iter_np_batches(ds):
            for c in self.columns:
                values[c].update(np.asarray(batch[c]).tolist())
        for c in self.columns:
            ordered = sorted(values[c], key=lambda v: (str(type(v)), v))
            self.stats_[f"unique_values({c})"] = ordered

    def _transform_numpy(self, batch: dict) -> dict:
        for c in self.columns:
            col = np.asarray(batch.pop(c))
            for v in self.stats_[f"unique_values({c})"]:
                batch[f"{c}_{v}"] = (col == v).astype(np.int8)
        return batch


class SimpleImputer(Preprocessor):
    """Fill NaNs: strategy "mean" (fitted per column) or "constant"
    (``fill_value``, no fit needed)."""

    def __init__(self, columns: list[str], strategy: str = "mean",
                 fill_value: float | None = None):
        super().__init__()
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        self._is_fittable = strategy != "constant"
        if not self._is_fittable:
            self._fitted = True

    def _fit(self, ds) -> None:
        n = {c: 0 for c in self.columns}
        s = {c: 0.0 for c in self.columns}
        for batch in _iter_np_batches(ds):
            for c in self.columns:
                col = np.asarray(batch[c], np.float64)
                live = ~np.isnan(col)
                n[c] += int(live.sum())
                s[c] += float(col[live].sum())
        for c in self.columns:
            self.stats_[f"mean({c})"] = s[c] / max(n[c], 1)

    def _transform_numpy(self, batch: dict) -> dict:
        for c in self.columns:
            col = np.asarray(batch[c], np.float64)
            fill = (self.fill_value if self.strategy == "constant"
                    else self.stats_[f"mean({c})"])
            batch[c] = np.where(np.isnan(col), fill, col).astype(np.float32)
        return batch


class Concatenator(Preprocessor):
    """Stack numeric columns into ONE 2-D feature column (the
    model-input shape ``iter_batches`` feeds to jax) — ref
    concatenator.py."""

    _is_fittable = False

    def __init__(self, columns: list[str] | None = None,
                 output_column_name: str = "concat",
                 exclude: list[str] | None = None):
        super().__init__()
        self.columns = list(columns) if columns is not None else None
        self.output_column_name = output_column_name
        self.exclude = set(exclude or [])
        self._fitted = True

    def _transform_numpy(self, batch: dict) -> dict:
        cols = (self.columns if self.columns is not None
                else [c for c in batch if c not in self.exclude])
        parts = []
        for c in cols:
            a = np.asarray(batch.pop(c), np.float32)
            parts.append(a[:, None] if a.ndim == 1 else a.reshape(len(a), -1))
        batch[self.output_column_name] = np.concatenate(parts, axis=1)
        return batch


class Chain(Preprocessor):
    """Sequential preprocessors: each stage fits on the PREVIOUS stage's
    transformed output (ref chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)
        # fittability derives from the stages (reference chain.py): a
        # chain of stateless stages needs no fit before transform
        self._is_fittable = any(p._is_fittable for p in self.preprocessors)
        if not self._is_fittable:
            self._fitted = True

    def _fit(self, ds) -> None:
        for p in self.preprocessors:
            if p._is_fittable:
                p.fit(ds)
            ds = p.transform(ds)

    def _transform_numpy(self, batch: dict) -> dict:
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch

