"""Dataset: the user-facing lazy, distributed data API.

Reference: ``python/ray/data/dataset.py`` (Dataset), ``read_api.py:340``.
Transforms build a logical chain; execution lowers it to physical ops and
streams blocks through the object store (SURVEY.md §3.6).
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterator

from ..core import api as ray
from . import datasource as ds
from . import logical as L
from .block import BlockAccessor, batch_to_block, concat_blocks
from .executor import StreamingExecutor, plan
from .iterator import DataIterator, SplitCoordinator, batches_from_blocks


class ActorPoolStrategy:
    """Actor-pool compute for map_batches (reference
    ``ray.data.ActorPoolStrategy``): ``size`` stateful worker actors."""

    def __init__(self, size: int = 1, **_compat):
        self.size = max(1, int(_compat.get("max_size", size)))


class Dataset:
    def __init__(self, last_op: L.LogicalOp):
        self._last_op = last_op

    # ------------------------------------------------------------ transforms
    def _chain(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    fn_kwargs: dict | None = None, compute=None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: dict | None = None,
                    ray_actor_options: dict | None = None,
                    **_ignored) -> "Dataset":
        """``compute=ActorPoolStrategy(size=n)`` runs the fn on a pool of
        stateful actors — pass a CLASS and it is constructed once per
        actor (the model-inference pattern). A class fn without an
        explicit compute defaults to a single-actor pool."""
        if compute is None and isinstance(fn, type):
            compute = ActorPoolStrategy(size=1)
        return self._chain(L.MapBatches(
            "map_batches", self._last_op, fn=fn, batch_format=batch_format,
            fn_kwargs=fn_kwargs or {}, compute=compute,
            fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs or {},
            ray_actor_options=ray_actor_options))

    def union(self, *others: "Dataset") -> "MaterializedDataset":
        """Concatenate datasets (materializes each input's blocks)."""
        refs = []
        for part in (self, *others):
            refs.extend(part.iter_internal_ref_bundles())
        return MaterializedDataset(refs)

    def map(self, fn: Callable) -> "Dataset":
        return self._chain(L.MapRows("map", self._last_op, fn=fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._chain(L.FlatMap("flat_map", self._last_op, fn=fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._chain(L.Filter("filter", self._last_op, fn=fn))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._chain(L.Repartition("repartition", self._last_op, num_blocks=num_blocks))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._chain(L.RandomShuffle("random_shuffle", self._last_op, seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._chain(L.Sort("sort", self._last_op, key=key, descending=descending))

    def limit(self, n: int) -> "Dataset":
        return self._chain(L.Limit("limit", self._last_op, limit=n))

    def groupby(self, key: str) -> "GroupedData":
        """Group rows by a column (reference ``grouped_data.py:21``); the
        aggregation executes as a hash-partitioned map-reduce exchange."""
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: int | None = None) -> "Dataset":
        """Hash join on column ``on`` (reference ``Dataset.join``). Both
        sides are hash-partitioned on the key; each reduce joins one
        partition pair with arrow's native join."""
        right_refs = list(other.iter_internal_ref_bundles())
        return self._chain(L.Join(
            "join", self._last_op, key=on, join_type=how,
            right_refs=right_refs, num_out=num_partitions))

    def zip(self, other: "Dataset") -> "Dataset":
        """Positionally merge columns of two datasets with equal row
        counts (reference ``Dataset.zip``). Blocks are re-aligned on row
        boundaries (a count pass, then one task per left block holding at
        most the overlapping right blocks); overlapping column names from
        ``other`` get a ``_1`` suffix."""
        left_refs = list(self.iter_internal_ref_bundles())
        right_refs = list(other.iter_internal_ref_bundles())
        count_remote = ray.remote(_count_task)
        left_counts = ray.get([count_remote.remote(r) for r in left_refs], timeout=300)
        right_counts = ray.get([count_remote.remote(r) for r in right_refs], timeout=300)
        if sum(left_counts) != sum(right_counts):
            raise ValueError(
                f"zip requires equal row counts: {sum(left_counts)} vs {sum(right_counts)}")
        right_starts = [0]
        for c in right_counts:
            right_starts.append(right_starts[-1] + c)
        zip_remote = ray.remote(_zip_task)
        out = []
        lo = 0
        for i, ref in enumerate(left_refs):
            hi = lo + left_counts[i]
            # right blocks overlapping [lo, hi) + their slice offsets
            overlaps = []
            blocks = []
            for j in builtins.range(len(right_refs)):
                s, e = right_starts[j], right_starts[j + 1]
                if e <= lo or s >= hi or s == e:
                    continue
                overlaps.append((max(lo, s) - s, min(hi, e) - s))
                blocks.append(right_refs[j])
            out.append(zip_remote.remote(ref, overlaps, *blocks))
            lo = hi
        return MaterializedDataset(out)

    # ------------------------------------------------------------ execution
    def iter_internal_ref_bundles(self) -> Iterator:
        executor = StreamingExecutor(plan(self._last_op))
        # retained so stats() can report the LAST execution's per-op
        # breakdown (reference data/_internal/stats.py)
        self._last_exec_ops = executor._ops
        return executor.run()

    def _iter_blocks(self) -> Iterator:
        for ref in self.iter_internal_ref_bundles():
            yield ray.get(ref, timeout=300)

    def materialize(self) -> "MaterializedDataset":
        refs = list(self.iter_internal_ref_bundles())
        return MaterializedDataset(refs)

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy", drop_last: bool = False):
        return batches_from_blocks(
            self._iter_blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last)

    def iter_rows(self) -> Iterator[dict]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def take(self, n: int = 20) -> list[dict]:
        out: list[dict] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self._iter_blocks())

    def schema(self):
        for block in self._iter_blocks():
            if block.num_rows:
                return block.schema
        return None

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        return concat_blocks(list(self._iter_blocks())).to_pandas()

    def stats(self) -> str:
        """Per-operator execution summary of the most recent run
        (reference ``data/_internal/stats.py`` — surfaced on the dataset
        after iteration). Executes the pipeline if it never ran."""
        if getattr(self, "_last_exec_ops", None) is None:
            n = len(self.materialize()._refs)
        else:
            n = None
        lines = ["Dataset execution stats:"]
        total = 0.0
        for op in self._last_exec_ops:
            s = op.stats
            total += s["wall_s"]
            avg = s["wall_s"] / s["tasks"] * 1000 if s["tasks"] else 0.0
            lines.append(
                f"  {op.name}: {s['tasks']} tasks, "
                f"{s['blocks_out']} blocks, "
                f"wall {s['wall_s']:.3f}s (avg {avg:.1f}ms/task)")
        lines.append(f"  total task wall: {total:.3f}s")
        if n is not None:
            lines.append(f"  output blocks: {n}")
        return "\n".join(lines)

    # --------------------------------------------------------- train feeding
    def streaming_split(self, n: int, *, equal: bool = False) -> list[DataIterator]:
        """Reference: dataset.py:1598 — coordinator actor deals blocks to n
        consumers (one per train worker). num_cpus=0: the coordinator only
        shuffles refs and must never occupy a schedulable slot."""
        coord_cls = ray.remote(SplitCoordinator)
        # Unnamed: the handle-GC kills the coordinator when the last driver
        # handle drops, so repeated splits can't accumulate actors.
        coord = coord_cls.options(num_cpus=0).remote(self, n, equal)
        return [DataIterator(coord, i) for i in builtins.range(n)]

    def split(self, n: int) -> list["MaterializedDataset"]:
        mat = self.materialize()
        refs = mat._refs
        bounds = [round(i * len(refs) / n) for i in builtins.range(n + 1)]
        return [MaterializedDataset(refs[bounds[i]:bounds[i + 1]]) for i in builtins.range(n)]

    # ---------------------------------------------------------------- writes
    # All writers are pyarrow.fs-backed (reference storage.py:358): `path`
    # may be a local dir or a filesystem URI (gs://bucket/dir, s3://…).
    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        for i, block in enumerate(self._iter_blocks()):
            with ds.open_output(path, f"part-{i:05d}.parquet") as f:
                pq.write_table(block, f)

    def write_json(self, path: str) -> None:
        import json

        for i, block in enumerate(self._iter_blocks()):
            def encode(o):
                if hasattr(o, "tolist"):
                    return o.tolist()  # numpy arrays round-trip as JSON lists
                return str(o)

            with ds.open_output(path, f"part-{i:05d}.json") as f:
                for row in BlockAccessor.for_block(block).iter_rows():
                    f.write((json.dumps(row, default=encode) + "\n").encode())

    def write_csv(self, path: str) -> None:
        import pyarrow.csv as pcsv

        for i, block in enumerate(self._iter_blocks()):
            with ds.open_output(path, f"part-{i:05d}.csv") as f:
                pcsv.write_csv(block, f)

    def write_tfrecords(self, path: str) -> None:
        """tf.train.Example shards (native codec, tfrecords.py)."""
        from .tfrecords import encode_example, write_record

        for i, block in enumerate(self._iter_blocks()):
            with ds.open_output(path, f"part-{i:05d}.tfrecords") as f:
                for row in BlockAccessor.for_block(block).iter_rows():
                    write_record(f, encode_example(row))

    def write_avro(self, path: str) -> None:
        """Avro Object Container File shards (native codec, avro.py —
        schema inferred per block from the columns; no avro/fastavro
        dependency)."""
        from .avro import write_container

        for i, block in enumerate(self._iter_blocks()):
            with ds.open_output(path, f"part-{i:05d}.avro") as f:
                write_container(
                    f, list(BlockAccessor.for_block(block).iter_rows()))

    def write_webdataset(self, path: str) -> None:
        """Tar shards in the webdataset layout (one member per column per
        row, grouped by key — webdataset.py; one shard per block)."""
        from .webdataset import write_shard

        start = 0
        for i, block in enumerate(self._iter_blocks()):
            with ds.open_output(path, f"part-{i:05d}.tar") as f:
                start += write_shard(
                    f, BlockAccessor.for_block(block).iter_rows(),
                    start_index=start)

    def __repr__(self):
        return f"Dataset(ops={[o.name for o in self._last_op.chain()]})"


def _count_task(block) -> int:
    return block.num_rows


def _zip_task(left, slices: list, *right_blocks):
    """Concat the right-side slices aligned to this left block, then merge
    columns (suffixing duplicates with ``_1``, reference zip semantics)."""
    import pyarrow as pa

    pieces = [b.slice(s, e - s) for b, (s, e) in zip(right_blocks, slices)]
    right = concat_blocks(pieces) if pieces else left.slice(0, 0)
    cols = {name: left.column(name) for name in left.column_names}
    for name in right.column_names:
        out_name = name if name not in cols else f"{name}_1"
        cols[out_name] = right.column(name)
    return pa.table(cols)


class GroupedData:
    """Result of ``Dataset.groupby`` (reference ``grouped_data.py:21``):
    aggregations lower to a hash-partitioned exchange with map-side
    partial aggregation."""

    def __init__(self, dataset: Dataset, key: str):
        self._dataset = dataset
        self._key = key

    def _agg(self, aggs: list) -> Dataset:
        return self._dataset._chain(L.GroupByAggregate(
            "groupby", self._dataset._last_op, key=self._key, aggs=aggs))

    def count(self) -> Dataset:
        return self._agg([(self._key, "count")])

    def sum(self, on: str) -> Dataset:
        return self._agg([(on, "sum")])

    def min(self, on: str) -> Dataset:
        return self._agg([(on, "min")])

    def max(self, on: str) -> Dataset:
        return self._agg([(on, "max")])

    def mean(self, on: str) -> Dataset:
        return self._agg([(on, "mean")])

    def aggregate(self, *aggs: tuple) -> Dataset:
        """``aggregate((col, "sum"), (col2, "max"), ...)``"""
        return self._agg(list(aggs))

    def map_groups(self, fn) -> Dataset:
        """Apply ``fn(batch_dict) -> batch_dict | list[row]`` to each
        group (reference ``GroupedData.map_groups``)."""
        return self._dataset._chain(L.GroupByAggregate(
            "groupby", self._dataset._last_op, key=self._key, aggs=None,
            map_groups_fn=fn))


class MaterializedDataset(Dataset):
    """Blocks pinned in the object store. Reference: MaterializedDataset."""

    def __init__(self, refs: list):
        self._refs = refs

        def make(r):
            return lambda: ray.get(r, timeout=120)

        # chained transforms re-read the pinned blocks from the object store
        super().__init__(L.Read("materialized", read_tasks=[make(r) for r in refs]))

    def iter_internal_ref_bundles(self) -> Iterator:
        return iter(self._refs)

    def num_blocks(self) -> int:
        return len(self._refs)

    def __repr__(self):
        return f"MaterializedDataset({len(self._refs)} blocks)"


# ------------------------------------------------------------------ read api


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset(L.Read("read_range", read_tasks=ds.range_tasks(n, parallelism)))


def from_items(items: list, *, parallelism: int = 8) -> Dataset:
    return Dataset(L.Read("read_items", read_tasks=ds.items_tasks(items, parallelism)))


def read_parquet(paths, *, row_groups_per_task: int | None = 4) -> Dataset:
    """Read parquet files (local paths, globs, dirs, or gs://-style URIs).
    Tasks split at row-group granularity so datasets larger than host RAM
    stream through the executor as bounded blocks."""
    return Dataset(L.Read("read_parquet", read_tasks=ds.parquet_tasks(
        paths, row_groups_per_task=row_groups_per_task)))


def read_csv(paths) -> Dataset:
    return Dataset(L.Read("read_csv", read_tasks=ds.csv_tasks(paths)))


def read_json(paths) -> Dataset:
    return Dataset(L.Read("read_json", read_tasks=ds.json_tasks(paths)))


def read_numpy(paths, *, column: str = "data") -> Dataset:
    return Dataset(L.Read("read_numpy", read_tasks=ds.numpy_tasks(paths, column)))


def from_numpy(arr, *, column: str = "data") -> MaterializedDataset:
    block = batch_to_block({column: arr})
    return MaterializedDataset([ray.put(block)])


def from_pandas(df) -> MaterializedDataset:
    import pyarrow as pa

    return MaterializedDataset([ray.put(pa.Table.from_pandas(df, preserve_index=False))])


def from_arrow(table) -> MaterializedDataset:
    return MaterializedDataset([ray.put(table)])


def read_text(paths) -> Dataset:
    """One row per line, column ``text`` (reference ``read_text``)."""
    return Dataset(L.Read("read_text", read_tasks=ds.text_tasks(paths)))


def read_binary_files(paths) -> Dataset:
    """One row per file: columns ``path`` and ``bytes``."""
    return Dataset(L.Read("read_binary", read_tasks=ds.binary_tasks(paths)))


def read_tfrecords(paths) -> Dataset:
    """TFRecord shards of tf.train.Example records, parsed natively (no
    TensorFlow import) — reference
    ``datasource/tfrecords_datasource.py``. One read task per shard."""
    from .tfrecords import tfrecords_tasks

    return Dataset(L.Read("read_tfrecords", read_tasks=tfrecords_tasks(paths)))


def read_avro(paths) -> Dataset:
    """Avro Object Container Files, parsed natively (no fastavro import)
    — reference ``read_api.py read_avro``. One read task per container
    file; long/double/boolean/string/bytes columns, arrays thereof, and
    nullable unions decode to plain python values."""
    from .avro import avro_tasks

    return Dataset(L.Read("read_avro", read_tasks=avro_tasks(paths)))


def read_webdataset(paths) -> Dataset:
    """WebDataset tar shards: members group into samples by basename
    stem, decoded by extension (json/txt/cls/... — bytes otherwise), with
    the stem in a ``__key__`` column. One streaming read task per shard
    (reference ``datasource/webdataset_datasource.py``)."""
    from .webdataset import webdataset_tasks

    return Dataset(L.Read("read_webdataset", read_tasks=webdataset_tasks(paths)))


def from_huggingface(hf_dataset, *, parallelism: int = 8) -> Dataset:
    """A HuggingFace ``datasets.Dataset`` by its underlying Arrow table
    (zero-copy slicing — reference ``datasource/huggingface_datasource``).
    Also accepts any object exposing ``.data`` as an Arrow table, or a
    plain iterable of row dicts."""
    import pyarrow as pa

    table = None
    data = getattr(hf_dataset, "data", None)
    if data is not None:
        table = getattr(data, "table", data)  # datasets wraps in ConcatenationTable
    if isinstance(hf_dataset, pa.Table):
        table = hf_dataset
    if table is None:
        return from_items(list(hf_dataset), parallelism=parallelism)
    if hasattr(table, "combine_chunks"):
        table = table.combine_chunks()
    n = table.num_rows
    parallelism = max(1, min(parallelism, n or 1))
    bounds = [round(i * n / parallelism) for i in builtins.range(parallelism + 1)]
    slices = [table.slice(bounds[i], bounds[i + 1] - bounds[i])
              for i in builtins.range(parallelism)]

    def make(s):
        return lambda: s

    return Dataset(L.Read("from_huggingface",
                          read_tasks=[make(s) for s in slices]))


def read_images(paths, *, size: tuple[int, int] | None = None,
                mode: str | None = None) -> Dataset:
    """Decode images into an ``image`` tensor column + ``path`` (reference
    ``datasource/image_datasource.py``). ``size=(h, w)`` resizes, ``mode``
    converts (e.g. "RGB")."""
    return Dataset(L.Read("read_images", read_tasks=ds.images_tasks(
        paths, size=size, mode=mode)))
