"""Dataset: the user-facing lazy, distributed data API.

Reference: ``python/ray/data/dataset.py`` (Dataset), ``read_api.py:340``.
Transforms build a logical chain; execution lowers it to physical ops and
streams blocks through the object store (SURVEY.md §3.6).
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterator

from ..core import api as ray
from . import datasource as ds
from . import logical as L
from .block import BlockAccessor, batch_to_block, concat_blocks
from .executor import StreamingExecutor, plan
from .iterator import DataIterator, SplitCoordinator, batches_from_blocks


class ActorPoolStrategy:
    """Actor-pool compute for map_batches (reference
    ``ray.data.ActorPoolStrategy``): ``size`` stateful worker actors."""

    def __init__(self, size: int = 1, **_compat):
        self.size = max(1, int(_compat.get("max_size", size)))


class Dataset:
    def __init__(self, last_op: L.LogicalOp):
        self._last_op = last_op

    # ------------------------------------------------------------ transforms
    def _chain(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    fn_kwargs: dict | None = None, compute=None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: dict | None = None,
                    **_ignored) -> "Dataset":
        """``compute=ActorPoolStrategy(size=n)`` runs the fn on a pool of
        stateful actors — pass a CLASS and it is constructed once per
        actor (the model-inference pattern). A class fn without an
        explicit compute defaults to a single-actor pool."""
        if compute is None and isinstance(fn, type):
            compute = ActorPoolStrategy(size=1)
        return self._chain(L.MapBatches(
            "map_batches", self._last_op, fn=fn, batch_format=batch_format,
            fn_kwargs=fn_kwargs or {}, compute=compute,
            fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs or {}))

    def union(self, *others: "Dataset") -> "MaterializedDataset":
        """Concatenate datasets (materializes each input's blocks)."""
        refs = []
        for part in (self, *others):
            refs.extend(part.iter_internal_ref_bundles())
        return MaterializedDataset(refs)

    def map(self, fn: Callable) -> "Dataset":
        return self._chain(L.MapRows("map", self._last_op, fn=fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._chain(L.FlatMap("flat_map", self._last_op, fn=fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._chain(L.Filter("filter", self._last_op, fn=fn))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._chain(L.Repartition("repartition", self._last_op, num_blocks=num_blocks))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._chain(L.RandomShuffle("random_shuffle", self._last_op, seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._chain(L.Sort("sort", self._last_op, key=key, descending=descending))

    def limit(self, n: int) -> "Dataset":
        return self._chain(L.Limit("limit", self._last_op, limit=n))

    # ------------------------------------------------------------ execution
    def iter_internal_ref_bundles(self) -> Iterator:
        executor = StreamingExecutor(plan(self._last_op))
        return executor.run()

    def _iter_blocks(self) -> Iterator:
        for ref in self.iter_internal_ref_bundles():
            yield ray.get(ref, timeout=300)

    def materialize(self) -> "MaterializedDataset":
        refs = list(self.iter_internal_ref_bundles())
        return MaterializedDataset(refs)

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy", drop_last: bool = False):
        return batches_from_blocks(
            self._iter_blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last)

    def iter_rows(self) -> Iterator[dict]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def take(self, n: int = 20) -> list[dict]:
        out: list[dict] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(b.num_rows for b in self._iter_blocks())

    def schema(self):
        for block in self._iter_blocks():
            if block.num_rows:
                return block.schema
        return None

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        return concat_blocks(list(self._iter_blocks())).to_pandas()

    def stats(self) -> str:
        mat = self.materialize()
        return f"Dataset: {len(mat._refs)} blocks"

    # --------------------------------------------------------- train feeding
    def streaming_split(self, n: int, *, equal: bool = False) -> list[DataIterator]:
        """Reference: dataset.py:1598 — coordinator actor deals blocks to n
        consumers (one per train worker). num_cpus=0: the coordinator only
        shuffles refs and must never occupy a schedulable slot."""
        coord_cls = ray.remote(SplitCoordinator)
        # Unnamed: the handle-GC kills the coordinator when the last driver
        # handle drops, so repeated splits can't accumulate actors.
        coord = coord_cls.options(num_cpus=0).remote(self, n, equal)
        return [DataIterator(coord, i) for i in builtins.range(n)]

    def split(self, n: int) -> list["MaterializedDataset"]:
        mat = self.materialize()
        refs = mat._refs
        bounds = [round(i * len(refs) / n) for i in builtins.range(n + 1)]
        return [MaterializedDataset(refs[bounds[i]:bounds[i + 1]]) for i in builtins.range(n)]

    # ---------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_blocks()):
            pq.write_table(block, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_json(self, path: str) -> None:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_blocks()):
            def encode(o):
                if hasattr(o, "tolist"):
                    return o.tolist()  # numpy arrays round-trip as JSON lists
                return str(o)

            with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                for row in BlockAccessor.for_block(block).iter_rows():
                    f.write(json.dumps(row, default=encode) + "\n")

    def write_csv(self, path: str) -> None:
        import os

        import pyarrow.csv as pcsv

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_blocks()):
            pcsv.write_csv(block, os.path.join(path, f"part-{i:05d}.csv"))

    def __repr__(self):
        return f"Dataset(ops={[o.name for o in self._last_op.chain()]})"


class MaterializedDataset(Dataset):
    """Blocks pinned in the object store. Reference: MaterializedDataset."""

    def __init__(self, refs: list):
        self._refs = refs

        def make(r):
            return lambda: ray.get(r, timeout=120)

        # chained transforms re-read the pinned blocks from the object store
        super().__init__(L.Read("materialized", read_tasks=[make(r) for r in refs]))

    def iter_internal_ref_bundles(self) -> Iterator:
        return iter(self._refs)

    def num_blocks(self) -> int:
        return len(self._refs)

    def __repr__(self):
        return f"MaterializedDataset({len(self._refs)} blocks)"


# ------------------------------------------------------------------ read api


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset(L.Read("read_range", read_tasks=ds.range_tasks(n, parallelism)))


def from_items(items: list, *, parallelism: int = 8) -> Dataset:
    return Dataset(L.Read("read_items", read_tasks=ds.items_tasks(items, parallelism)))


def read_parquet(paths) -> Dataset:
    return Dataset(L.Read("read_parquet", read_tasks=ds.parquet_tasks(paths)))


def read_csv(paths) -> Dataset:
    return Dataset(L.Read("read_csv", read_tasks=ds.csv_tasks(paths)))


def read_json(paths) -> Dataset:
    return Dataset(L.Read("read_json", read_tasks=ds.json_tasks(paths)))


def read_numpy(paths, *, column: str = "data") -> Dataset:
    return Dataset(L.Read("read_numpy", read_tasks=ds.numpy_tasks(paths, column)))


def from_numpy(arr, *, column: str = "data") -> MaterializedDataset:
    block = batch_to_block({column: arr})
    return MaterializedDataset([ray.put(block)])


def from_pandas(df) -> MaterializedDataset:
    import pyarrow as pa

    return MaterializedDataset([ray.put(pa.Table.from_pandas(df, preserve_index=False))])


def from_arrow(table) -> MaterializedDataset:
    return MaterializedDataset([ray.put(table)])


def read_text(paths) -> Dataset:
    """One row per line, column ``text`` (reference ``read_text``)."""
    return Dataset(L.Read("read_text", read_tasks=ds.text_tasks(paths)))


def read_binary_files(paths) -> Dataset:
    """One row per file: columns ``path`` and ``bytes``."""
    return Dataset(L.Read("read_binary", read_tasks=ds.binary_tasks(paths)))
