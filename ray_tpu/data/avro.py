"""Avro datasource: Object Container Files without an avro dependency.

Reference: ``python/ray/data/read_api.py`` ``read_avro`` (which parses
via the ``fastavro`` package). This module implements the container
format and a binary codec for the subset a columnar roundtrip needs,
natively (ROADMAP item 8, closing the readers backlog):

  * Container framing (the Avro 1.11 spec's Object Container File):
    magic ``Obj\\x01``, a file-metadata map carrying ``avro.schema``
    (JSON) + ``avro.codec`` (``null`` — no compression dependency), a
    16-byte sync marker, then blocks of ``count | byte_size | records |
    sync``.
  * Binary encoding: zig-zag varint longs, little-endian IEEE doubles,
    length-prefixed string/bytes, 1-byte booleans, block-encoded arrays,
    ``["null", T]`` unions for nullable columns, one top-level record
    per row.

The writer infers the record schema from the rows' columns (long /
double / boolean / string / bytes, arrays thereof, nullable via union);
the reader decodes any schema built from those primitives.
"""

from __future__ import annotations

import hashlib
import io
import json
import struct
from typing import Callable, Iterator

MAGIC = b"Obj\x01"

# --------------------------------------------------------------- primitives


def _write_long(out: bytearray, value: int) -> None:
    """Zig-zag varint (the Avro ``long`` wire format)."""
    n = (value << 1) ^ (value >> 63)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_long(stream) -> int:
    result = shift = 0
    while True:
        raw = stream.read(1)
        if not raw:
            raise EOFError("truncated avro long")
        b = raw[0]
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (result >> 1) ^ -(result & 1)


def _write_bytes(out: bytearray, value: bytes) -> None:
    _write_long(out, len(value))
    out += value


def _read_bytes(stream) -> bytes:
    n = _read_long(stream)
    data = stream.read(n)
    if len(data) < n:
        raise EOFError("truncated avro bytes")
    return data


# ------------------------------------------------------------ schema values


def _encode_value(out: bytearray, schema, value) -> None:
    if isinstance(schema, list):  # union: [null, T]
        if value is None:
            _write_long(out, schema.index("null"))
            return
        idx = next(i for i, s in enumerate(schema) if s != "null")
        _write_long(out, idx)
        _encode_value(out, schema[idx], value)
        return
    if isinstance(schema, dict) and schema.get("type") == "array":
        value = list(value)
        if value:
            _write_long(out, len(value))
            for v in value:
                _encode_value(out, schema["items"], v)
        _write_long(out, 0)  # terminator
        return
    if isinstance(schema, dict) and schema.get("type") == "record":
        for field in schema["fields"]:
            _encode_value(out, field["type"], value.get(field["name"]))
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.append(1 if value else 0)
        return
    if schema == "long":
        _write_long(out, int(value))
        return
    if schema == "double":
        out += struct.pack("<d", float(value))
        return
    if schema == "string":
        _write_bytes(out, str(value).encode())
        return
    if schema == "bytes":
        _write_bytes(out, bytes(value))
        return
    raise TypeError(f"unsupported avro schema {schema!r}")


def _decode_value(stream, schema):
    if isinstance(schema, list):  # union
        idx = _read_long(stream)
        return _decode_value(stream, schema[idx])
    if isinstance(schema, dict) and schema.get("type") == "array":
        out = []
        while True:
            count = _read_long(stream)
            if count == 0:
                return out
            if count < 0:  # spec: negative count is followed by byte size
                _read_long(stream)
                count = -count
            for _ in range(count):
                out.append(_decode_value(stream, schema["items"]))
    if isinstance(schema, dict) and schema.get("type") == "record":
        return {f["name"]: _decode_value(stream, f["type"])
                for f in schema["fields"]}
    if isinstance(schema, dict):  # {"type": "long"} wrapper form
        return _decode_value(stream, schema["type"])
    if schema == "null":
        return None
    if schema == "boolean":
        return stream.read(1)[0] != 0
    if schema in ("long", "int"):
        return _read_long(stream)
    if schema == "double":
        return struct.unpack("<d", stream.read(8))[0]
    if schema == "float":
        return struct.unpack("<f", stream.read(4))[0]
    if schema == "string":
        return _read_bytes(stream).decode()
    if schema == "bytes":
        return _read_bytes(stream)
    raise TypeError(f"unsupported avro schema {schema!r}")


# --------------------------------------------------------- schema inference


def _primitive_for(value):
    if hasattr(value, "item") and not hasattr(value, "__len__"):
        value = value.item()  # numpy scalar
    if isinstance(value, bool):
        return "boolean"
    if isinstance(value, int):
        return "long"
    if isinstance(value, float):
        return "double"
    if isinstance(value, str):
        return "string"
    if isinstance(value, bytes):
        return "bytes"
    return None


def _merge_prim(a: str | None, b: str | None) -> str | None:
    if a is None or a == b:
        return b
    if b is None:
        return a
    if {a, b} == {"long", "double"}:
        return "double"
    raise TypeError(f"column mixes avro types {a!r} and {b!r}")


def infer_schema(rows: list[dict], name: str = "row") -> dict:
    """Record schema over the union of the rows' columns: long / double /
    boolean / string / bytes, arrays thereof, ``["null", T]`` unions for
    columns with missing values."""
    cols: dict[str, dict] = {}
    for row in rows:
        for key in row:
            cols.setdefault(key, {"prim": None, "array": False,
                                  "nullable": False})
    for row in rows:
        for key, spec in cols.items():
            value = row.get(key)
            if value is None:
                spec["nullable"] = True
                continue
            if hasattr(value, "tolist"):  # numpy array/scalar
                value = value.tolist()
            if isinstance(value, (list, tuple)):
                spec["array"] = True
                for v in value:
                    spec["prim"] = _merge_prim(spec["prim"], _primitive_for(v))
            else:
                prim = _primitive_for(value)
                if prim is None:
                    raise TypeError(
                        f"column {key!r}: cannot map {type(value).__name__} "
                        "to an avro type")
                spec["prim"] = _merge_prim(spec["prim"], prim)
    fields = []
    for key, spec in sorted(cols.items()):
        t: object = spec["prim"] or "string"
        if spec["array"]:
            t = {"type": "array", "items": t}
        if spec["nullable"]:
            t = ["null", t]
        fields.append({"name": key, "type": t})
    return {"type": "record", "name": name, "fields": fields}


# ------------------------------------------------------------ container IO


def write_container(stream, rows: list[dict], schema: dict | None = None,
                    block_rows: int = 1000) -> int:
    """Write rows as one Avro Object Container File; returns rows
    written. Values are normalized through ``tolist`` so numpy columns
    round-trip as plain python."""
    rows = [
        {k: (v.tolist() if hasattr(v, "tolist") else v) for k, v in r.items()}
        for r in rows
    ]
    if schema is None:
        schema = infer_schema(rows)
    schema_json = json.dumps(schema).encode()
    sync = hashlib.md5(schema_json).digest()  # any 16 bytes; deterministic
    header = bytearray(MAGIC)
    _write_long(header, 2)  # metadata map: one block of two entries
    _write_bytes(header, b"avro.schema")
    _write_bytes(header, schema_json)
    _write_bytes(header, b"avro.codec")
    _write_bytes(header, b"null")
    _write_long(header, 0)  # map terminator
    header += sync
    stream.write(bytes(header))
    for start in range(0, len(rows), block_rows):
        chunk = rows[start:start + block_rows]
        body = bytearray()
        for row in chunk:
            _encode_value(body, schema, row)
        block = bytearray()
        _write_long(block, len(chunk))
        _write_long(block, len(body))
        block += body
        block += sync
        stream.write(bytes(block))
    return len(rows)


def read_container(stream) -> list[dict]:
    """Parse one Object Container File into its rows."""
    if stream.read(4) != MAGIC:
        raise ValueError("not an avro object container file (bad magic)")
    meta: dict[str, bytes] = {}
    while True:
        count = _read_long(stream)
        if count == 0:
            break
        if count < 0:
            _read_long(stream)  # byte size of the block, unused
            count = -count
        for _ in range(count):
            key = _read_bytes(stream).decode()
            meta[key] = _read_bytes(stream)
    codec = meta.get("avro.codec", b"null")
    if codec not in (b"null", b""):
        raise ValueError(f"unsupported avro codec {codec!r} "
                         "(only 'null' — uncompressed — is built in)")
    schema = json.loads(meta["avro.schema"])
    sync = stream.read(16)
    rows: list[dict] = []
    while True:
        try:
            count = _read_long(stream)
        except EOFError:
            return rows
        size = _read_long(stream)
        block = stream.read(size)
        if len(block) < size:
            raise EOFError("truncated avro block")
        buf = io.BytesIO(block)
        for _ in range(count):
            rows.append(_decode_value(buf, schema))
        if stream.read(16) != sync:
            raise ValueError("avro sync marker mismatch (corrupt shard?)")


# ---------------------------------------------------------------- read tasks


def avro_tasks(paths) -> list[Callable]:
    """One read task per container file (the file-parallel split every
    other datasource uses)."""
    from . import datasource as ds

    def make(fs, path):
        def task():
            import pyarrow as pa

            with fs.open_input_stream(path) as f:
                # container blocks are sequential; buffer once
                rows = read_container(io.BytesIO(f.read()))
            cols: dict[str, list] = {}
            for r in rows:
                for k in r:
                    cols.setdefault(k, [])
            for r in rows:
                for k, col in cols.items():
                    col.append(r.get(k))
            return pa.table(cols) if cols else pa.table({})
        return task

    return [make(fs, path) for fs, path in ds._expand_paths(paths)]
