"""WebDataset datasource: tar-sharded sample archives (ROADMAP item 8).

Reference: ``python/ray/data/_internal/datasource/webdataset_datasource.py``
and the webdataset convention itself: a shard is a plain ``.tar`` file
whose members group into samples by basename stem — ``000017.jpg``,
``000017.txt`` and ``000017.json`` are one sample with columns ``jpg``,
``txt`` and ``json``. Members of one sample are stored contiguously, so
shards stream sequentially (the property that makes the format fast on
object stores; no random access needed — we read with ``tarfile`` in
streaming mode).

Decoding is by extension, mirroring the reference's default decoder
table: ``json`` → parsed object, text-ish extensions → ``str``,
``cls``/``cls2``/``index`` → ``int``, everything else stays raw
``bytes``. An extra ``__key__`` column carries the sample stem.

Writing inverts the mapping: every row becomes one basename stem, every
column one member named ``<key>.<column>`` (bytes written raw, str as
UTF-8, anything else as JSON).
"""

from __future__ import annotations

import io
import json
import posixpath
import tarfile
from typing import Callable

_TEXT_EXTS = {"txt", "text", "transcript", "caption", "cap"}
_INT_EXTS = {"cls", "cls2", "index", "label"}


def _decode_member(ext: str, payload: bytes):
    if ext == "json":
        return json.loads(payload.decode())
    if ext in _TEXT_EXTS:
        return payload.decode()
    if ext in _INT_EXTS:
        return int(payload.decode().strip())
    return payload


def _encode_member(ext: str, value) -> bytes:
    if isinstance(value, bytes):
        return value
    if isinstance(value, str):
        return value.encode()
    if hasattr(value, "tolist"):  # numpy scalar/array
        value = value.tolist()
    if ext in _INT_EXTS and isinstance(value, int):
        return str(value).encode()
    return json.dumps(value).encode()


def _split_member(name: str) -> tuple[str, str]:
    """``dir/000017.seg.json`` → (stem ``dir/000017``, ext ``seg.json``
    lowered to its last component for decoding). The FIRST dot after the
    basename starts the extension (webdataset convention: extensions may
    themselves be dotted)."""
    dirname, _, base = name.rpartition("/")
    stem, dot, ext = base.partition(".")
    if dirname:
        stem = f"{dirname}/{stem}"
    return stem, ext if dot else ""


def iter_samples(fileobj) -> "list[dict]":
    """Group a tar stream's members into samples by stem, in order."""
    samples: list[dict] = []
    current_key: str | None = None
    current: dict = {}
    with tarfile.open(fileobj=fileobj, mode="r|*") as tf:
        for member in tf:
            if not member.isfile():
                continue
            stem, ext = _split_member(member.name)
            if not ext:
                continue
            if stem != current_key:
                if current:
                    samples.append(current)
                current_key, current = stem, {"__key__": stem}
            payload = tf.extractfile(member).read()
            current[ext] = _decode_member(ext.rpartition(".")[2].lower(),
                                          payload)
    if current:
        samples.append(current)
    return samples


def webdataset_tasks(paths) -> list[Callable]:
    """One read task per tar shard (the reference's file-parallel split)."""
    from . import datasource as ds

    def make(fs, path):
        def task():
            import pyarrow as pa

            with fs.open_input_stream(path) as f:
                # tarfile streaming mode wants a file-like with read();
                # pyarrow streams provide it directly.
                rows = iter_samples(f)
            cols: dict[str, list] = {}
            for r in rows:
                for k in r:
                    cols.setdefault(k, [])
            for r in rows:
                for k, col in cols.items():
                    col.append(r.get(k))
            return pa.table(cols) if cols else pa.table({})
        return task

    return [make(fs, path) for fs, path in ds._expand_paths(paths)]


def write_shard(stream, rows, *, start_index: int = 0) -> int:
    """Write rows as one webdataset tar shard; returns rows written.
    Row keys come from a ``__key__`` column when present, else zero-padded
    sequence numbers."""
    count = 0
    with tarfile.open(fileobj=stream, mode="w") as tf:
        for i, row in enumerate(rows):
            key = row.get("__key__") or f"{start_index + i:08d}"
            for col, value in row.items():
                if col == "__key__" or value is None:
                    continue
                payload = _encode_member(col.rpartition(".")[2].lower(), value)
                info = tarfile.TarInfo(name=f"{key}.{col}")
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))
            count += 1
    return count
