"""Blocks: the unit of data movement. Arrow tables in the object store.

Reference: ``python/ray/data/block.py`` + ``_internal/arrow_block.py``.
Blocks are immutable pyarrow Tables (zero-copy via plasma + pickle5
out-of-band buffers); ``BlockAccessor`` adapts them to user-facing batch
formats (numpy / pandas / pyarrow), numpy being the TPU-relevant one
(host staging before ``jax.device_put``).
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np
import pyarrow as pa


def build_block(rows: list) -> pa.Table:
    """Build an Arrow block from a list of rows (dicts or scalars)."""
    if rows and not isinstance(rows[0], dict):
        rows = [{"item": r} for r in rows]
    if not rows:
        return pa.table({})
    cols: dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        for k in cols:
            cols[k].append(r.get(k))
    arrays, fields = {}, []
    for k, v in cols.items():
        if v and isinstance(v[0], np.ndarray) and v[0].ndim >= 1:
            # Same tensor machinery as batch_to_block, so multi-dim row
            # values (e.g. images through map/filter rebuilds) keep their
            # shape metadata instead of flattening.
            arr, shape_meta = _tensor_array(np.stack(v))
            arrays[k] = arr
            meta = {TENSOR_SHAPE_META: shape_meta} if shape_meta else None
            fields.append(pa.field(k, arr.type, metadata=meta))
        else:
            arrays[k] = _to_array(v)
            fields.append(pa.field(k, arrays[k].type))
    return pa.table(arrays, schema=pa.schema(fields))


# Field-metadata key recording a tensor column's per-row shape, so >2-D
# tensors (e.g. HWC images) round-trip through the FixedSizeList storage
# (reference: ArrowTensorType extension metadata).
TENSOR_SHAPE_META = b"ray_tpu.tensor_shape"


def _tensor_array(v: np.ndarray) -> tuple[pa.Array, bytes | None]:
    arr = pa.FixedSizeListArray.from_arrays(
        pa.array(v.reshape(-1)), int(np.prod(v.shape[1:])))
    shape = None
    if v.ndim > 2:
        import json

        shape = json.dumps(list(v.shape[1:])).encode()
    return arr, shape


def _to_array(values: list) -> pa.Array:
    if values and isinstance(values[0], np.ndarray):
        flat = np.stack(values)
        return pa.FixedSizeListArray.from_arrays(
            pa.array(flat.reshape(-1)), flat.size // len(values)
        )
    return pa.array(values)


def _row_shape(col_field: pa.Field):
    """Per-row tensor shape from field metadata (None = flat width)."""
    meta = col_field.metadata or {}
    if TENSOR_SHAPE_META in meta:
        import json

        return tuple(json.loads(meta[TENSOR_SHAPE_META]))
    return None


def batch_to_block(batch: Any) -> pa.Table:
    """Normalize a user-returned batch (dict of arrays / pandas / arrow /
    list of rows) into an Arrow block."""
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        cols = {}
        fields = []
        for k, v in batch.items():
            if not isinstance(v, np.ndarray):
                v = list(v)
                if any(isinstance(x, bytes) for x in v):
                    # Binary stays off the numpy path: fixed-width S dtype
                    # silently truncates values at NUL bytes.
                    cols[k] = pa.array(v)
                    fields.append(pa.field(k, cols[k].type))
                    continue
                v = np.asarray(v)  # lists-of-lists -> 2D -> FixedSizeList
            if v.ndim > 1:
                arr, shape_meta = _tensor_array(v)
                cols[k] = arr
                meta = {TENSOR_SHAPE_META: shape_meta} if shape_meta else None
                fields.append(pa.field(k, arr.type, metadata=meta))
            else:
                cols[k] = pa.array(v)
                fields.append(pa.field(k, cols[k].type))
        return pa.table(cols, schema=pa.schema(fields))
    if isinstance(batch, list):
        return build_block(batch)
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ModuleNotFoundError:
        pass
    raise TypeError(f"unsupported batch type {type(batch)}")


class BlockAccessor:
    """Reference: block.py BlockAccessor."""

    def __init__(self, block: pa.Table):
        self._block = block
        # Per-column flattened tensor cache: _row would otherwise
        # re-flatten the whole column per row (O(n^2) take_all).
        self._flat_cache: dict[str, np.ndarray] = {}

    @staticmethod
    def for_block(block: pa.Table) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        return self._block.num_rows

    def size_bytes(self) -> int:
        return self._block.nbytes

    def schema(self):
        return self._block.schema

    def slice(self, start: int, end: int) -> pa.Table:
        return self._block.slice(start, end - start)

    def to_numpy(self) -> dict[str, np.ndarray]:
        out = {}
        for idx, name in enumerate(self._block.column_names):
            col = self._block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                flat = col.combine_chunks().flatten().to_numpy(zero_copy_only=False)
                shape = _row_shape(self._block.schema.field(idx)) or (width,)
                out[name] = flat.reshape((self._block.num_rows,) + tuple(shape))
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self._block.to_pandas()

    def to_arrow(self) -> pa.Table:
        return self._block

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "default"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self._block
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterable[dict]:
        for i in range(self._block.num_rows):
            yield self._row(i)

    def _row(self, i: int) -> dict:
        out = {}
        for idx, name in enumerate(self._block.column_names):
            col = self._block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                flat = self._flat_cache.get(name)
                if flat is None:
                    flat = self._flat_cache[name] = (
                        col.combine_chunks().flatten().to_numpy(zero_copy_only=False))
                value = flat[i * width:(i + 1) * width]
                shape = _row_shape(self._block.schema.field(idx))
                out[name] = value.reshape(shape) if shape else value
            else:
                out[name] = col[i].as_py()
        return out


def concat_blocks(blocks: list[pa.Table]) -> pa.Table:
    blocks = [b for b in blocks if b.num_rows]
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks)
