"""Datasources: read task factories.

Reference: ``python/ray/data/read_api.py:340`` + ``datasource/`` (30+
sources; the file-based ones here cover the formats in the baked image:
parquet/csv/json/numpy + in-memory items/range).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**", "*"), recursive=True)
                if os.path.isfile(f)
            ))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range_tasks(n: int, parallelism: int) -> list[Callable]:
    parallelism = max(1, min(parallelism, n)) if n else 1
    bounds = [round(i * n / parallelism) for i in range(parallelism + 1)]

    def make(lo, hi):
        def read():
            import numpy as np

            return {"id": np.arange(lo, hi, dtype=np.int64)}

        return read

    return [make(bounds[i], bounds[i + 1]) for i in range(parallelism)]


def items_tasks(items: list, parallelism: int) -> list[Callable]:
    from .block import build_block

    parallelism = max(1, min(parallelism, len(items))) if items else 1
    bounds = [round(i * len(items) / parallelism) for i in range(parallelism + 1)]

    def make(chunk):
        return lambda: build_block(chunk)

    return [make(items[bounds[i]:bounds[i + 1]]) for i in range(parallelism)]


def parquet_tasks(paths) -> list[Callable]:
    files = _expand_paths(paths)

    def make(f):
        def read():
            import pyarrow.parquet as pq

            return pq.read_table(f)

        return read

    return [make(f) for f in files]


def csv_tasks(paths) -> list[Callable]:
    files = _expand_paths(paths)

    def make(f):
        def read():
            import pyarrow.csv as pcsv

            return pcsv.read_csv(f)

        return read

    return [make(f) for f in files]


def json_tasks(paths) -> list[Callable]:
    files = _expand_paths(paths)

    def make(f):
        def read():
            import pyarrow.json as pjson

            return pjson.read_json(f)

        return read

    return [make(f) for f in files]


def text_tasks(paths) -> list[Callable]:
    files = _expand_paths(paths)

    def make(f):
        def read():
            with open(f, "r", encoding="utf-8", errors="replace") as fh:
                return {"text": [line.rstrip("\n") for line in fh]}

        return read

    return [make(f) for f in files]


def binary_tasks(paths) -> list[Callable]:
    files = _expand_paths(paths)

    def make(f):
        def read():
            with open(f, "rb") as fh:
                return {"path": [f], "bytes": [fh.read()]}

        return read

    return [make(f) for f in files]


def numpy_tasks(paths, column: str = "data") -> list[Callable]:
    files = _expand_paths(paths)

    def make(f):
        def read():
            import numpy as np

            return {column: np.load(f)}

        return read

    return [make(f) for f in files]
