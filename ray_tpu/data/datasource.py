"""Datasources: read task factories over pyarrow.fs filesystems.

Reference: ``python/ray/data/read_api.py:340`` + ``datasource/`` (30+
sources) and the pyarrow.fs-backed persistence layer
(``train/_internal/storage.py:358``). Every reader/writer accepts local
paths, globs, directories, AND filesystem URIs (``gs://``, ``s3://``,
``file://`` — anything ``pyarrow.fs.FileSystem.from_uri`` resolves), so
training ingest from cloud buckets — the TPU-native default — uses the
same code path as local files.

Parquet reads split at ROW-GROUP granularity: a dataset far larger than
host RAM streams through the executor as bounded tasks instead of
one-task-per-file loading whole files.
"""

from __future__ import annotations

import fnmatch
import os
import posixpath
from typing import Callable


def resolve_filesystem(path: str):
    """``path`` -> (pyarrow FileSystem, fs-local path). URIs pick their
    scheme's filesystem; bare paths are local."""
    from pyarrow import fs as pafs

    if "://" in path:
        return pafs.FileSystem.from_uri(path)
    return pafs.LocalFileSystem(), path


def _glob_match(pattern: str, path: str) -> bool:
    """Segment-wise glob: ``*``/``?``/``[...]`` never cross ``/`` (glob
    semantics, unlike raw fnmatch) and a ``**`` segment matches any number
    of segments."""
    def match(pseg: list[str], sseg: list[str]) -> bool:
        if not pseg:
            return not sseg
        if pseg[0] == "**":
            return any(match(pseg[1:], sseg[i:]) for i in range(len(sseg) + 1))
        if not sseg:
            return False
        return fnmatch.fnmatch(sseg[0], pseg[0]) and match(pseg[1:], sseg[1:])

    return match(pattern.split("/"), path.split("/"))


def _list_files(fs, base: str, is_local: bool) -> list[str]:
    from pyarrow import fs as pafs

    if any(ch in base for ch in "*?["):
        if is_local:
            import glob as _glob

            # Local globs keep stdlib glob semantics exactly (relative
            # patterns, no root scans).
            return sorted(f for f in _glob.glob(base, recursive=True)
                          if os.path.isfile(f))
        # Remote glob: list under the deepest fixed prefix, match with
        # glob (not fnmatch) semantics. A pattern with no fixed prefix
        # would mean scanning the bucket root — reject it as ambiguous.
        fixed = []
        for p in base.split("/"):
            if any(ch in p for ch in "*?["):
                break
            fixed.append(p)
        root = "/".join(fixed)
        if not root:
            raise ValueError(
                f"glob {base!r} has no fixed prefix to list from; "
                "anchor it (e.g. bucket/dir/*.parquet)")
        sel = pafs.FileSelector(root, recursive=True)
        return sorted(
            f.path for f in fs.get_file_info(sel)
            if f.type == pafs.FileType.File and _glob_match(base, f.path)
        )
    info = fs.get_file_info(base)
    if info.type == pafs.FileType.File:
        return [base]
    if info.type == pafs.FileType.Directory:
        sel = pafs.FileSelector(base, recursive=True)
        return sorted(
            f.path for f in fs.get_file_info(sel)
            if f.type == pafs.FileType.File
        )
    return []


def _expand_paths(paths) -> list[tuple]:
    """Expand paths/globs/dirs/URIs into [(fs, file_path)] pairs."""
    if isinstance(paths, str):
        paths = [paths]
    out: list[tuple] = []
    for p in paths:
        fs, local = resolve_filesystem(p)
        files = _list_files(
            fs, local, is_local="://" not in p or p.startswith("file://"))
        out.extend((fs, f) for f in files)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def range_tasks(n: int, parallelism: int) -> list[Callable]:
    parallelism = max(1, min(parallelism, n)) if n else 1
    bounds = [round(i * n / parallelism) for i in range(parallelism + 1)]

    def make(lo, hi):
        def read():
            import numpy as np

            return {"id": np.arange(lo, hi, dtype=np.int64)}

        return read

    return [make(bounds[i], bounds[i + 1]) for i in range(parallelism)]


def items_tasks(items: list, parallelism: int) -> list[Callable]:
    from .block import build_block

    parallelism = max(1, min(parallelism, len(items))) if items else 1
    bounds = [round(i * len(items) / parallelism) for i in range(parallelism + 1)]

    def make(chunk):
        return lambda: build_block(chunk)

    return [make(items[bounds[i]:bounds[i + 1]]) for i in range(parallelism)]


def parquet_tasks(paths, *, row_groups_per_task: int | None = 4) -> list[Callable]:
    """One task per ``row_groups_per_task`` row groups (None = whole
    file): metadata-only planning, so multi-GB files stream through the
    executor as bounded blocks instead of materializing whole (reference:
    ParquetDatasource fragment splitting)."""
    import logging

    import pyarrow.parquet as pq

    files = _expand_paths(paths)

    def make(fs, f, groups=None):
        def read():
            pf = pq.ParquetFile(fs.open_input_file(f))
            if groups is None:
                return pf.read()
            return pf.read_row_groups(groups)

        return read

    if row_groups_per_task is None:
        return [make(fs, f) for fs, f in files]

    def probe(pair):
        fs, f = pair
        try:
            with fs.open_input_file(f) as fh:
                return pq.ParquetFile(fh).metadata.num_row_groups
        except Exception as e:
            logging.getLogger(__name__).warning(
                "parquet footer probe failed for %s (%s); reading whole file",
                f, e)
            return None

    # Footer probes run concurrently — over a cloud filesystem each is a
    # remote round trip, and hundreds of serial ones would stall dataset
    # construction for minutes.
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(16, max(1, len(files)))) as pool:
        group_counts = list(pool.map(probe, files))

    tasks: list[Callable] = []
    for (fs, f), n_groups in zip(files, group_counts):
        if n_groups is None or n_groups <= row_groups_per_task:
            tasks.append(make(fs, f))
        else:
            for start in range(0, n_groups, row_groups_per_task):
                tasks.append(make(fs, f, groups=list(
                    range(start, min(start + row_groups_per_task, n_groups)))))
    return tasks


def csv_tasks(paths) -> list[Callable]:
    def make(fs, f):
        def read():
            import pyarrow.csv as pcsv

            with fs.open_input_stream(f) as fh:
                return pcsv.read_csv(fh)

        return read

    return [make(fs, f) for fs, f in _expand_paths(paths)]


def json_tasks(paths) -> list[Callable]:
    def make(fs, f):
        def read():
            import pyarrow.json as pjson

            with fs.open_input_stream(f) as fh:
                return pjson.read_json(fh)

        return read

    return [make(fs, f) for fs, f in _expand_paths(paths)]


def text_tasks(paths) -> list[Callable]:
    def make(fs, f):
        def read():
            with fs.open_input_stream(f) as fh:
                text = fh.read().decode("utf-8", errors="replace")
            lines = text.split("\n")
            if lines and lines[-1] == "":
                lines.pop()
            return {"text": lines}

        return read

    return [make(fs, f) for fs, f in _expand_paths(paths)]


def binary_tasks(paths) -> list[Callable]:
    def make(fs, f):
        def read():
            with fs.open_input_stream(f) as fh:
                return {"path": [f], "bytes": [fh.read()]}

        return read

    return [make(fs, f) for fs, f in _expand_paths(paths)]


def numpy_tasks(paths, column: str = "data") -> list[Callable]:
    def make(fs, f):
        def read():
            import io

            import numpy as np

            with fs.open_input_stream(f) as fh:
                return {column: np.load(io.BytesIO(fh.read()))}

        return read

    return [make(fs, f) for fs, f in _expand_paths(paths)]


_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def images_tasks(paths, *, size: tuple[int, int] | None = None,
                 mode: str | None = None, files_per_task: int = 16) -> list[Callable]:
    """Decode image files into an ``image`` tensor column (+ ``path``).
    ``size=(h, w)`` resizes; ``mode`` converts (e.g. "RGB" / "L")
    (reference ``datasource/image_datasource.py``)."""
    pairs = [(fs, f) for fs, f in _expand_paths(paths)
             if f.lower().endswith(_IMAGE_EXTS)]
    if not pairs:
        raise FileNotFoundError(f"no image files matched {paths}")

    def make(chunk):
        def read():
            import io
            import logging

            import numpy as np
            from PIL import Image, UnidentifiedImageError

            from .block import batch_to_block

            images, names = [], []
            for fs, f in chunk:
                with fs.open_input_stream(f) as fh:
                    try:
                        img = Image.open(io.BytesIO(fh.read()))
                    except UnidentifiedImageError:
                        logging.getLogger(__name__).warning(
                            "skipping undecodable image %s", f)
                        continue
                    if mode:
                        img = img.convert(mode)
                    if size:
                        img = img.resize((size[1], size[0]))
                    images.append(np.asarray(img))
                    names.append(f)
            if not images:
                import pyarrow as pa

                return pa.table({})
            if len({im.shape for im in images}) > 1:
                raise ValueError(
                    "images have differing shapes "
                    f"({sorted({im.shape for im in images})}); pass "
                    "size=(h, w) (and mode=) to normalize them")
            return batch_to_block({"image": np.stack(images),
                                   "path": np.asarray(names)})

        return read

    return [make(pairs[i:i + files_per_task])
            for i in range(0, len(pairs), files_per_task)]


# ------------------------------------------------------------------ writers


def open_output(path: str, name: str):
    """(fs, dir)-aware writer helper: ensures the directory and opens
    ``dir/name`` for writing on the right filesystem."""
    fs, local = resolve_filesystem(path)
    fs.create_dir(local, recursive=True)
    return fs.open_output_stream(posixpath.join(local, name))
