"""Logical operators + plan.

Reference: ``python/ray/data/_internal/logical/`` — logical ops are a DAG
of declarative nodes; the planner lowers them to physical operators, and
the optimizer fuses adjacent one-to-one maps into a single task per block.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class LogicalOp:
    name: str
    input: "LogicalOp | None" = None

    def chain(self) -> list["LogicalOp"]:
        ops: list[LogicalOp] = []
        op: LogicalOp | None = self
        while op is not None:
            ops.append(op)
            op = op.input
        return list(reversed(ops))


@dataclasses.dataclass
class Read(LogicalOp):
    """Leaf: produces read tasks, each yielding one block."""

    read_tasks: list[Callable[[], Any]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MapBatches(LogicalOp):
    fn: Callable = None
    batch_format: str = "numpy"
    fn_kwargs: dict = dataclasses.field(default_factory=dict)
    # Actor-pool compute (ActorPoolStrategy) for stateful fns; None = tasks.
    compute: Any = None
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = dataclasses.field(default_factory=dict)
    ray_actor_options: dict | None = None  # e.g. {"resources": {"TPU": 1}}


@dataclasses.dataclass
class MapRows(LogicalOp):
    fn: Callable = None


@dataclasses.dataclass
class FlatMap(LogicalOp):
    fn: Callable = None


@dataclasses.dataclass
class Filter(LogicalOp):
    fn: Callable = None


@dataclasses.dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1


@dataclasses.dataclass
class RandomShuffle(LogicalOp):
    seed: int | None = None


@dataclasses.dataclass
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False


@dataclasses.dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclasses.dataclass
class Union(LogicalOp):
    others: list[LogicalOp] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class GroupByAggregate(LogicalOp):
    """groupby(key) + aggregations or map_groups (reference
    ``grouped_data.py:21``)."""

    key: str = ""
    aggs: list = None  # [(col, "count"|"sum"|"min"|"max"|"mean")]
    map_groups_fn: Any = None
    num_out: int | None = None


@dataclasses.dataclass
class Join(LogicalOp):
    """Hash join against a pre-materialized right side (reference
    ``Dataset.join``)."""

    key: str = ""
    join_type: str = "inner"
    right_refs: list = dataclasses.field(default_factory=list)
    num_out: int | None = None
