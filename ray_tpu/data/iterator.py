"""DataIterator: batch iteration and train-worker stream splitting.

Reference: ``python/ray/data/iterator.py:94`` (iter_batches) and
``dataset.py:1598`` streaming_split via a SplitCoordinator actor feeding
one iterator per train worker.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from ..core import api as ray
from .block import BlockAccessor, concat_blocks


def batches_from_blocks(
    block_iter,
    *,
    batch_size: int | None,
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator:
    """Re-slice a stream of blocks into fixed-size batches."""
    if batch_size is None:
        for block in block_iter:
            if block.num_rows:
                yield BlockAccessor.for_block(block).to_batch(batch_format)
        return
    carry = []
    carry_rows = 0
    for block in block_iter:
        carry.append(block)
        carry_rows += block.num_rows
        while carry_rows >= batch_size:
            merged = concat_blocks(carry)
            batch = merged.slice(0, batch_size)
            rest = merged.slice(batch_size, merged.num_rows - batch_size)
            carry = [rest] if rest.num_rows else []
            carry_rows = rest.num_rows
            yield BlockAccessor.for_block(batch).to_batch(batch_format)
    if carry_rows and not drop_last:
        merged = concat_blocks(carry)
        yield BlockAccessor.for_block(merged).to_batch(batch_format)


class SplitCoordinator:
    """Actor that owns a dataset's output stream and deals blocks to n
    consumers (reference: StreamSplitDataIterator's coordinator).

    Blocks are dealt round-robin to per-split queues so every consumer gets
    a fair share regardless of polling order. Scheduled with num_cpus=0
    (it only shuffles refs) so it never starves the cluster."""

    def __init__(self, dataset, n: int, equal: bool = False):
        self._iter = dataset.iter_internal_ref_bundles()
        self._n = n
        self._equal = equal
        self._queues: list[list] = [[] for _ in range(n)]
        self._delivered = [0] * n
        self._next_split = 0
        self._exhausted = False
        self._finished: set[int] = set()

    def _pull_until(self, split_idx: int) -> None:
        while not self._queues[split_idx] and not self._exhausted:
            try:
                ref = next(self._iter)
            except StopIteration:
                self._exhausted = True
                if self._equal:
                    # equal=True: trim trailing imbalance so every split
                    # sees the same number of blocks (reference: equal
                    # splits drop the remainder).
                    floor = min(self._delivered[i] + len(self._queues[i]) for i in range(self._n))
                    for i in range(self._n):
                        excess = self._delivered[i] + len(self._queues[i]) - floor
                        if excess > 0:
                            del self._queues[i][-excess:]
                return
            self._queues[self._next_split].append(ref)
            self._next_split = (self._next_split + 1) % self._n

    def next_block_ref(self, split_idx: int):
        """Returns the next block ref for this split, or None when its
        share of the stream is exhausted."""
        self._pull_until(split_idx)
        if self._queues[split_idx]:
            self._delivered[split_idx] += 1
            return self._queues[split_idx].pop(0)
        return None

    def mark_finished(self, split_idx: int) -> bool:
        """Consumer i is done; returns True when ALL consumers are done
        (the last one kills this actor to release its slot)."""
        self._finished.add(split_idx)
        return len(self._finished) >= self._n


class DataIterator:
    """Per-worker view of a split stream."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx
        self._exhausted = False

    def _blocks(self):
        if self._exhausted:
            return  # second epoch over a drained one-shot stream is empty
        from ..core.status import ActorDiedError

        while True:
            try:
                ref = ray.get(self._coord.next_block_ref.remote(self._idx), timeout=120)
            except ActorDiedError:
                # coordinator reclaimed by another consumer's final kill
                self._exhausted = True
                return
            if ref is None:
                self._exhausted = True
                # Last finished consumer reclaims the coordinator actor so a
                # leaked slot can't starve later scheduling (advisor round 1).
                try:
                    all_done = ray.get(self._coord.mark_finished.remote(self._idx), timeout=30)
                    if all_done:
                        ray.kill(self._coord)
                except Exception:
                    pass
                return
            yield ray.get(ref, timeout=120)

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy", drop_last: bool = False):
        return batches_from_blocks(
            self._blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
        )

    def iter_rows(self):
        for block in self._blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def to_device_batches(self, *, batch_size: int, sharding=None,
                          batch_format: str = "numpy", drop_last: bool = True):
        """TPU idiom: host batch → ``jax.device_put`` (async HBM prefetch
        with one batch of lookahead double-buffering)."""
        import jax

        prev = None
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format,
                                       drop_last=drop_last):
            arrs = {k: np.asarray(v) for k, v in batch.items()}
            cur = jax.device_put(arrs, sharding) if sharding else jax.device_put(arrs)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev
