"""DataIterator: batch iteration and train-worker stream splitting.

Reference: ``python/ray/data/iterator.py:94`` (iter_batches) and
``dataset.py:1598`` streaming_split via a SplitCoordinator actor feeding
one iterator per train worker.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from ..core import api as ray
from .block import BlockAccessor, concat_blocks


def batches_from_blocks(
    block_iter,
    *,
    batch_size: int | None,
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator:
    """Re-slice a stream of blocks into fixed-size batches."""
    if batch_size is None:
        for block in block_iter:
            if block.num_rows:
                yield BlockAccessor.for_block(block).to_batch(batch_format)
        return
    carry = []
    carry_rows = 0
    for block in block_iter:
        carry.append(block)
        carry_rows += block.num_rows
        while carry_rows >= batch_size:
            merged = concat_blocks(carry)
            batch = merged.slice(0, batch_size)
            rest = merged.slice(batch_size, merged.num_rows - batch_size)
            carry = [rest] if rest.num_rows else []
            carry_rows = rest.num_rows
            yield BlockAccessor.for_block(batch).to_batch(batch_format)
    if carry_rows and not drop_last:
        merged = concat_blocks(carry)
        yield BlockAccessor.for_block(merged).to_batch(batch_format)


class SplitCoordinator:
    """Actor that owns a dataset's output stream and deals blocks to n
    consumers (reference: StreamSplitDataIterator's coordinator)."""

    def __init__(self, dataset, n: int):
        self._iter = dataset.iter_internal_ref_bundles()
        self._n = n
        self._exhausted = False

    def next_block_ref(self, split_idx: int):
        """Returns the next block ref, or None when exhausted. Consumers
        share one stream; fairness comes from polling order."""
        if self._exhausted:
            return None
        try:
            return next(self._iter)
        except StopIteration:
            self._exhausted = True
            return None


class DataIterator:
    """Per-worker view of a split stream."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx

    def _blocks(self):
        while True:
            ref = ray.get(self._coord.next_block_ref.remote(self._idx), timeout=120)
            if ref is None:
                return
            yield ray.get(ref, timeout=120)

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy", drop_last: bool = False):
        return batches_from_blocks(
            self._blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last,
        )

    def iter_rows(self):
        for block in self._blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def to_device_batches(self, *, batch_size: int, sharding=None,
                          batch_format: str = "numpy", drop_last: bool = True):
        """TPU idiom: host batch → ``jax.device_put`` (async HBM prefetch
        with one batch of lookahead double-buffering)."""
        import jax

        prev = None
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format,
                                       drop_last=drop_last):
            arrs = {k: np.asarray(v) for k, v in batch.items()}
            cur = jax.device_put(arrs, sharding) if sharding else jax.device_put(arrs)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev
