"""Elastic resilience: preemption-aware checkpointing + bounded recovery.

The subsystem that turns a spot-slice preemption from a feared outage
into a measured event (ROADMAP item 6). Three pieces compose:

  * :mod:`ray_tpu.resilience.checkpoint` — async, atomically-committed
    train-state checkpoints, each committed version registered with the
    GCS so recovery finds the latest one without touching a dead node;
  * :mod:`ray_tpu.resilience.preemption` — the notice plumbing: hazard
    views over the GCS node table + the ``node_preempted`` ErrorEvent
    channel, consumed by the serve controller (proactive replica
    eviction) and the recovery bench;
  * the wiring that lives in the subsystems themselves: raylet draining
    (``core/raylet.py``), the ``preempt_slice`` FaultPlan kind
    (``chaos/plan.py``), train controller resume
    (``train/controller.py``), and ``bench.py run_recovery_bench``.
"""

from .checkpoint import (
    AsyncCheckpointManager,
    latest_committed,
    latest_registered,
    list_committed,
    load_checkpoint,
    register_latest,
)
from .metadata_watcher import GceMetadataPreemptionWatcher
from .preemption import PreemptionNotice, hazard_nodes

__all__ = [
    "AsyncCheckpointManager",
    "GceMetadataPreemptionWatcher",
    "PreemptionNotice",
    "hazard_nodes",
    "latest_committed",
    "latest_registered",
    "list_committed",
    "load_checkpoint",
    "register_latest",
]
