"""GCE metadata-server preemption watcher (ROADMAP item 10a).

GCE announces a spot/preemptible VM reclaim through the instance
metadata server: ``GET /computeMetadata/v1/instance/preempted`` (with
the ``Metadata-Flavor: Google`` header) flips from ``FALSE`` to ``TRUE``
roughly 30 seconds before the VM disappears. Polling that key on the
node itself and feeding the raylet's existing ``PreemptionNotice`` path
(``begin_draining``) turns a cloud reclaim into the same measured
drain → task-event flush → proactive-serve-eviction → replacement
pipeline the chaos ``preempt_slice`` rule exercises — with no RPC from
the control plane needed and no dependency on the autoscaler's slower
PREEMPTED-listing poll.

Enabled per-raylet via config: ``preempt_metadata_watch`` (off by
default — only GCE instances have a metadata server), with
``preempt_metadata_url`` / ``preempt_metadata_poll_s`` overridable for
tests (a fake HTTP endpoint) and exotic environments.
"""

from __future__ import annotations

import logging
import threading
import urllib.request

logger = logging.getLogger(__name__)

DEFAULT_METADATA_URL = ("http://metadata.google.internal/computeMetadata/"
                        "v1/instance/preempted")


class GceMetadataPreemptionWatcher:
    """Polls the instance metadata ``preempted`` key and fires
    ``on_preempted(reason)`` exactly once when it reads TRUE, then
    stops (the node is going away; there is nothing left to watch).

    Transport errors count in ``errors`` and never fire the callback —
    an unreachable metadata server must not drain a healthy node."""

    def __init__(self, on_preempted, url: str = DEFAULT_METADATA_URL,
                 poll_s: float = 1.0, timeout_s: float = 2.0):
        self._on_preempted = on_preempted
        self._url = url
        self._poll_s = max(0.05, poll_s)
        self._timeout_s = timeout_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.fired = False
        self.polls = 0
        self.errors = 0

    def poll_once(self) -> bool:
        """One metadata read; True iff the instance is being reclaimed."""
        req = urllib.request.Request(
            self._url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=self._timeout_s) as resp:
                body = resp.read().decode(errors="ignore").strip().upper()
        except Exception:
            self.errors += 1
            return False
        self.polls += 1
        return body == "TRUE"

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.poll_once():
                self.fired = True
                logger.warning(
                    "GCE metadata server reports this instance PREEMPTED "
                    "(%s): feeding the preemption-notice drain path",
                    self._url)
                try:
                    self._on_preempted("gce metadata: instance preempted")
                except Exception:
                    logger.exception("preemption callback failed")
                return  # one-shot: the VM is being reclaimed
            self._stop.wait(self._poll_s)

    def start(self) -> "GceMetadataPreemptionWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="gce-preempt-watch")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
