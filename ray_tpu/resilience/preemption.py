"""Preemption-notice plumbing: the hazard view consumers share.

A preemption flows through the cluster as:

  raylet (GCE notice / ``preempt_slice`` chaos rule / PreemptionNotice
  RPC) -> draining: stops admitting leases, flushes task events, reports
  ``ReportNodeDraining`` -> the GCS flags the node ``draining`` in the
  node table AND publishes a ``node_preempted`` ErrorEvent -> after the
  grace window the raylet kills its workers and the GCS marks the node
  DEAD (``NodePreempted``).

:func:`hazard_nodes` merges both signals (table flags + error events)
into one ``node_id -> PreemptionNotice`` view. The serve controller uses
it to evict replicas proactively; the recovery bench uses the notice
clocks to measure ``recovery_*_s`` SLOs. Clocks are chaos-clock stamps
(:mod:`ray_tpu.chaos.clock`), so a VirtualClock run measures virtual
seconds.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..chaos import clock as chaos_clock


@dataclass
class PreemptionNotice:
    node_id: str
    reason: str = ""
    notice_clock: float = 0.0  # chaos-clock stamp at the notice
    state: str = "DRAINING"    # DRAINING while in grace, DEAD after


def hazard_nodes(gcs_call) -> dict[str, PreemptionNotice]:
    """``node_id -> PreemptionNotice`` for every node that is draining,
    preempted-dead, or named in a ``node_preempted`` ErrorEvent.

    ``gcs_call(method, payload) -> dict`` is a synchronous GCS RPC (the
    worker's ``_gcs_call``). Never raises — an unreachable control plane
    yields an empty view, not a new failure.
    """
    out: dict[str, PreemptionNotice] = {}
    try:
        for node in gcs_call("GetAllNodes", {}).get("nodes", []):
            nid = node.get("node_id") or ""
            if not nid:
                continue
            if node.get("draining"):
                out[nid] = PreemptionNotice(
                    node_id=nid,
                    reason=node.get("drain_reason") or "",
                    notice_clock=float(node.get("drain_notice_clock")
                                       or chaos_clock.now()),
                    state="DEAD" if node.get("state") == "DEAD" else "DRAINING",
                )
    except Exception:
        return out
    try:
        reply = gcs_call("ListErrors", {"type": "node_preempted", "limit": 1000})
        for event in reply.get("errors", []):
            nid = event.get("node_id") or ""
            if not nid or nid in out:
                continue
            extra = event.get("extra") or {}
            out[nid] = PreemptionNotice(
                node_id=nid,
                reason=extra.get("reason") or "",
                notice_clock=float(extra.get("notice_clock") or chaos_clock.now()),
            )
    except Exception:
        pass
    return out
