"""Async, atomically-committed train-state checkpoints.

The failure mode this module exists for: a spot TPU slice is preempted
mid-train, and the last "checkpoint" on disk is a half-written directory
that *loads* (pickle happily reads a prefix that happens to frame) or a
complete one nobody can find because the node that knew about it is
gone. Both are fixed structurally:

  * **The train step never blocks on I/O.** ``save()`` snapshots the
    pytree to host memory synchronously (cheap) and hands it to ONE
    background writer thread. A save arriving while a write is in flight
    replaces any still-queued snapshot (latest-wins coalescing) — a slow
    disk degrades checkpoint *freshness*, never step time.
  * **Commits are atomic.** The writer serializes into a hidden temp
    directory, fsyncs the payload and a ``COMMITTED`` marker, then
    renames the directory to its final ``ckpt_<step>`` name and fsyncs
    the parent. Readers only trust directories whose marker exists, so a
    kill at ANY point leaves the previous version (or nothing) visible —
    never a corrupt, loadable-looking one.
  * **Every committed version is registered with the GCS** (KV entry per
    run name). Recovery resolves the latest checkpoint from the control
    plane, not from the dead worker's local state.

Reference inspiration: orbax's async checkpointing + Ray Train's
``CheckpointManager``; the commit-marker discipline is the classic
tmp+fsync+rename pattern databases use for their WAL segments.
"""

from __future__ import annotations

import contextlib
import copy
import json
import logging
import os
import shutil
import threading
import time
import uuid

logger = logging.getLogger(__name__)

GCS_KEY_PREFIX = "resilience:ckpt:"
COMMIT_MARKER = "COMMITTED"
_CKPT_PREFIX = "ckpt_"
_TMP_PREFIX = ".tmp-"


def _snapshot(tree):
    """Host-side copy of a (possibly on-device) pytree: the train loop may
    mutate/donate its buffers the moment save() returns."""
    try:
        import jax
        import numpy as np

        return jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, jax.Array) else copy.deepcopy(x),
            tree,
        )
    except Exception:
        return copy.deepcopy(tree)


def _fsync_dir(path: str) -> None:
    with contextlib.suppress(OSError):
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def _write_json_synced(path: str, data: dict) -> None:
    with open(path, "w") as f:
        json.dump(data, f, default=str)
        f.flush()
        os.fsync(f.fileno())


def _default_write(tree, path: str) -> None:
    from ..train.checkpoint import save_pytree

    save_pytree(tree, path)


def list_committed(root: str) -> list[tuple[int, str]]:
    """(step, path) for every COMMITTED checkpoint under ``root``,
    ascending by step. Directories without the marker (a commit that died
    mid-flight) are invisible."""
    out: list[tuple[int, str]] = []
    try:
        entries = os.listdir(root)
    except OSError:
        return out
    for name in entries:
        if not name.startswith(_CKPT_PREFIX):
            continue
        path = os.path.join(root, name)
        if not os.path.exists(os.path.join(path, COMMIT_MARKER)):
            continue
        try:
            out.append((int(name[len(_CKPT_PREFIX):]), path))
        except ValueError:
            continue
    out.sort()
    return out


def latest_committed(root: str) -> dict | None:
    """The newest committed version under ``root`` (local-scan fallback
    when no GCS registration is reachable)."""
    committed = list_committed(root)
    if not committed:
        return None
    step, path = committed[-1]
    return {"step": step, "path": path}


def load_checkpoint(path: str, *, like=None) -> tuple:
    """Load a committed checkpoint dir -> ``(tree, meta)``. Refuses
    uncommitted directories — a half-written checkpoint must never be
    mistaken for a real one."""
    if not os.path.exists(os.path.join(path, COMMIT_MARKER)):
        raise FileNotFoundError(
            f"{path}: no {COMMIT_MARKER} marker — not a committed checkpoint")
    from ..train.checkpoint import load_pytree

    tree = load_pytree(path, like=like)
    meta: dict = {}
    with contextlib.suppress(OSError, ValueError):
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
    return tree, meta


def register_latest(run_name: str, path: str, step: int) -> bool:
    """Record the latest committed version in the GCS KV so recovery can
    find it without touching the (possibly dead) writer node."""
    try:
        from ..core.worker import global_worker

        global_worker()._gcs_call("KvPut", {
            "key": GCS_KEY_PREFIX + run_name,
            "value": json.dumps({
                "path": path, "step": int(step), "ts": time.time(),
            }).encode(),
            "overwrite": True,
        })
        return True
    except Exception:
        return False


def latest_registered(run_name: str) -> dict | None:
    """The GCS-registered latest committed version for ``run_name``
    (``{"path", "step", "ts"}``), or None. Entries whose path no longer
    holds a commit marker are ignored (storage was GC'd or lost)."""
    try:
        from ..core.worker import global_worker

        reply = global_worker()._gcs_call("KvGet", {"key": GCS_KEY_PREFIX + run_name})
        if not reply.get("found"):
            return None
        entry = json.loads(reply["value"])
    except Exception:
        return None
    path = entry.get("path") or ""
    if not os.path.exists(os.path.join(path, COMMIT_MARKER)):
        return None
    return entry


class AsyncCheckpointManager:
    """Background-committed checkpoints with keep-K retention.

    ``save(step, tree)`` returns in snapshot time; serialization, fsync,
    and the atomic rename happen on a daemon writer thread. One pending
    snapshot is held at most: a newer save replaces an unwritten older
    one (the drop is counted — under a slow disk you keep the freshest
    state, not a backlog).
    """

    def __init__(self, root: str, *, run_name: str = "", keep_k: int | None = 2,
                 register_with_gcs: bool = True, write_fn=None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.run_name = run_name
        self.keep_k = keep_k
        self._register = register_with_gcs
        self._write_fn = write_fn or _default_write
        self._cv = threading.Condition()
        self._pending: tuple[int, object, dict] | None = None
        self._writing = False
        self._closed = False
        self.last_committed: dict | None = latest_committed(self.root)
        self.metrics = {"saves": 0, "commits": 0, "dropped": 0,
                        "commit_errors": 0, "max_save_block_ms": 0.0}
        self._thread = threading.Thread(
            target=self._writer_loop, daemon=True,
            name=f"async-ckpt-{run_name or 'anon'}")
        self._thread.start()

    # ------------------------------------------------------------- train side
    def save(self, step: int, tree, metrics: dict | None = None) -> float:
        """Snapshot ``tree`` and enqueue its commit. Returns the
        milliseconds the CALLER was blocked (snapshot only — the contract
        the non-blocking test asserts)."""
        t0 = time.perf_counter()
        snapshot = _snapshot(tree)
        with self._cv:
            if self._closed:
                raise RuntimeError("AsyncCheckpointManager is closed")
            if self._pending is not None:
                self.metrics["dropped"] += 1
            self._pending = (int(step), snapshot, dict(metrics or {}))
            self.metrics["saves"] += 1
            self._cv.notify_all()
        block_ms = (time.perf_counter() - t0) * 1000.0
        self.metrics["max_save_block_ms"] = max(
            self.metrics["max_save_block_ms"], block_ms)
        return block_ms

    def wait(self, timeout: float | None = 30.0) -> bool:
        """Block until every enqueued snapshot is committed (or timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending is not None or self._writing:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining if remaining is None else min(remaining, 0.5))
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Flush pending commits, then stop the writer thread."""
        self.wait(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------ writer side
    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait(0.5)
                if self._pending is None and self._closed:
                    return
                step, snapshot, metrics = self._pending
                self._pending = None
                self._writing = True
            try:
                self._commit(step, snapshot, metrics)
            except Exception:
                self.metrics["commit_errors"] += 1
                logger.exception("async checkpoint commit of step %d failed", step)
            finally:
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()

    def _commit(self, step: int, snapshot, metrics: dict) -> None:
        final = os.path.join(self.root, f"{_CKPT_PREFIX}{step:08d}")
        tmp = os.path.join(self.root, f"{_TMP_PREFIX}{step:08d}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            self._write_fn(snapshot, tmp)
            _write_json_synced(os.path.join(tmp, "meta.json"), {
                "step": step, "metrics": metrics, "ts": time.time(),
                "run_name": self.run_name,
            })
            # The marker is written LAST inside tmp; the rename publishes
            # marker+payload as one unit. Readers key on the marker, so
            # there is no window where a visible dir lacks its payload.
            _write_json_synced(os.path.join(tmp, COMMIT_MARKER), {"step": step})
            if os.path.exists(final):  # re-commit of the same step: replace
                shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
            _fsync_dir(self.root)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self.last_committed = {"step": step, "path": final}
        self.metrics["commits"] += 1
        if self._register and self.run_name:
            register_latest(self.run_name, final, step)
        self._gc()

    def _gc(self) -> None:
        if self.keep_k is None or self.keep_k <= 0:
            return
        committed = list_committed(self.root)
        for _step, path in committed[: max(0, len(committed) - self.keep_k)]:
            shutil.rmtree(path, ignore_errors=True)
