"""Job submission: run entrypoint scripts as managed cluster drivers.

Equivalent of the reference's job submission stack
(``dashboard/modules/job/job_manager.py``,
``dashboard/modules/job/sdk.py`` JobSubmissionClient): a job is a shell
entrypoint spawned as a driver subprocess with ``RAY_TPU_ADDRESS``
pointing at the running cluster, tracked through a
PENDING/RUNNING/SUCCEEDED/FAILED/STOPPED FSM with captured logs.
"""

from .job_manager import JobInfo, JobStatus, JobSubmissionClient

__all__ = ["JobInfo", "JobStatus", "JobSubmissionClient"]
