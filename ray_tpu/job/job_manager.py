"""Job manager actor + submission client.

Reference: ``dashboard/modules/job/job_manager.py`` (JobManager.submit_job
spawns a JobSupervisor that runs the entrypoint as a subprocess and
polls it to a terminal state) and ``job/common.py`` (JobStatus FSM).
Redesign: one detached named actor supervises all jobs (our actors are
cheap single-process asyncio, no per-job supervisor actor needed);
drivers attach to the cluster through ``RAY_TPU_ADDRESS``.
"""

from __future__ import annotations

import os
import re
import shlex
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field

from ..core import api as ray

JOB_MANAGER_NAME = "_JOB_MANAGER"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    runtime_env: dict = field(default_factory=dict)


class _JobManagerActor:
    def __init__(self, gcs_address: str, log_dir: str = "/tmp/ray_tpu/jobs"):
        self.gcs_address = gcs_address
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._jobs: dict[str, JobInfo] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit(self, entrypoint: str, runtime_env: dict | None = None,
               submission_id: str | None = None) -> str:
        if submission_id is not None and not re.fullmatch(r"[A-Za-z0-9._-]+", submission_id):
            raise ValueError(
                f"invalid submission_id {submission_id!r}: only letters, digits, "
                "'.', '_' and '-' are allowed (it names the log file)"
            )
        jid = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if jid in self._jobs:
                raise ValueError(f"job {jid} already exists")
            info = JobInfo(jid, entrypoint, runtime_env=runtime_env or {})
            self._jobs[jid] = info

        from ..core.runtime_env import apply_runtime_env

        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.gcs_address
        env["RAY_TPU_JOB_ID"] = jid
        cwd = apply_runtime_env(env, runtime_env)
        if cwd is not None and not os.path.isdir(cwd):
            info.status, info.message = JobStatus.FAILED, f"working_dir {cwd} not found"
            return jid

        log_path = os.path.join(self.log_dir, f"{jid}.log")
        try:
            proc = subprocess.Popen(
                entrypoint if isinstance(entrypoint, str) else shlex.join(entrypoint),
                shell=True,
                cwd=cwd,
                env=env,
                stdout=open(log_path, "wb"),
                stderr=subprocess.STDOUT,
            )
        except OSError as e:
            info.status, info.message = JobStatus.FAILED, str(e)
            return jid
        with self._lock:
            if info.status == JobStatus.STOPPED:
                # stop() won the race while we were spawning: honor it.
                proc.terminate()
                info.end_time = time.time()
                return jid
            info.status = JobStatus.RUNNING
            info.start_time = time.time()
            self._procs[jid] = proc
        threading.Thread(target=self._supervise, args=(jid, proc), daemon=True).start()
        return jid

    def _supervise(self, jid: str, proc: subprocess.Popen) -> None:
        code = proc.wait()
        with self._lock:
            info = self._jobs[jid]
            self._procs.pop(jid, None)
            if info.status == JobStatus.STOPPED:
                pass  # stop_job already finalized it
            elif code == 0:
                info.status = JobStatus.SUCCEEDED
            else:
                info.status = JobStatus.FAILED
                info.message = f"entrypoint exited with code {code}"
            info.end_time = time.time()

    def stop(self, jid: str) -> bool:
        with self._lock:
            info = self._jobs.get(jid)
            proc = self._procs.get(jid)
            if info is None or info.status in JobStatus.TERMINAL:
                return False
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        return True

    def status(self, jid: str) -> dict | None:
        with self._lock:
            info = self._jobs.get(jid)
            return asdict(info) if info else None

    def list(self) -> list[dict]:
        with self._lock:
            return [asdict(i) for i in self._jobs.values()]

    def logs(self, jid: str) -> str:
        path = os.path.join(self.log_dir, f"{jid}.log")
        try:
            with open(path, "rb") as f:
                return f.read().decode("utf-8", errors="replace")
        except OSError:
            return ""


class JobSubmissionClient:
    """Reference ``dashboard/modules/job/sdk.py``: submit/list/stop/logs
    against the (auto-created) job manager actor."""

    def __init__(self):
        try:
            self._mgr = ray.get_actor(JOB_MANAGER_NAME)
        except ValueError:
            from ..core.worker import global_worker

            gcs_address = global_worker().gcs_address
            self._mgr = ray.remote(_JobManagerActor).options(
                name=JOB_MANAGER_NAME, lifetime="detached", num_cpus=0,
                max_concurrency=16,
            ).remote(gcs_address)
            ray.get(self._mgr.list.remote(), timeout=60)  # wait until live

    def submit_job(self, *, entrypoint: str, runtime_env: dict | None = None,
                   submission_id: str | None = None) -> str:
        return ray.get(
            self._mgr.submit.remote(entrypoint, runtime_env, submission_id), timeout=60
        )

    def get_job_status(self, submission_id: str) -> str:
        info = ray.get(self._mgr.status.remote(submission_id), timeout=60)
        if info is None:
            raise ValueError(f"no such job: {submission_id}")
        return info["status"]

    def get_job_info(self, submission_id: str) -> JobInfo:
        info = ray.get(self._mgr.status.remote(submission_id), timeout=60)
        if info is None:
            raise ValueError(f"no such job: {submission_id}")
        return JobInfo(**info)

    def list_jobs(self) -> list[JobInfo]:
        return [JobInfo(**i) for i in ray.get(self._mgr.list.remote(), timeout=60)]

    def get_job_logs(self, submission_id: str) -> str:
        return ray.get(self._mgr.logs.remote(submission_id), timeout=60)

    def stop_job(self, submission_id: str) -> bool:
        return ray.get(self._mgr.stop.remote(submission_id), timeout=60)

    def wait_until_terminal(self, submission_id: str, timeout: float = 120.0) -> str:
        deadline = time.monotonic() + timeout
        while True:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            if time.monotonic() >= deadline:
                raise TimeoutError(f"job {submission_id} still {status} after {timeout}s")
            time.sleep(0.2)
