"""Worker group: N SPMD worker actors placed as one atomic unit.

Reference: ``python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:102`` and v1 ``backend_executor.py:226`` (placement
group creation). TPU delta (SURVEY.md §7.1): each worker is one host of a
slice; the group is scheduled with a placement group so the slice is
claimed atomically, and ``jax.distributed.initialize`` is the process-
group bootstrap (the reference's ``_setup_torch_process_group``,
``torch/config.py:66``, is the analogous step).
"""

from __future__ import annotations

import logging
import threading
import traceback

from ..core import api as ray
from ..util import PlacementGroupSchedulingStrategy, placement_group, remove_placement_group
from .checkpoint import Checkpoint
from .session import TrainContext, _Session, _set_session

logger = logging.getLogger(__name__)


class TrainWorker:
    """Actor hosting one SPMD process of the training job."""

    def __init__(self, world_rank: int, world_size: int, experiment_name: str,
                 storage_path: str):
        self._context = TrainContext(
            world_rank=world_rank,
            world_size=world_size,
            local_rank=0,
            local_world_size=1,
            node_rank=world_rank,
            experiment_name=experiment_name,
            storage_path=storage_path,
        )
        self._dataset_shards: dict = {}
        self._thread: threading.Thread | None = None
        self._session: _Session | None = None
        self._error: str | None = None
        self._done = False

    def get_coordinator_address(self) -> str:
        """Rank 0 picks the jax.distributed coordinator endpoint: its own IP
        plus a free port (``jax.distributed.initialize`` on process 0 binds
        and serves it)."""
        from ..parallel.distributed import pick_coordinator_address

        return pick_coordinator_address()

    def init_distributed(self, coordinator: str) -> bool:
        """``jax.distributed.initialize`` across the group — multi-host
        slices only (single-host groups share one process's devices)."""
        from ..parallel.distributed import initialize_process

        initialize_process(
            coordinator, self._context.world_size, self._context.world_rank)
        return True

    def set_dataset_shards(self, shards: dict) -> bool:
        """Receive this rank's DataIterator per dataset name (reference:
        ``dataset.py:1598`` streaming_split → per-worker iterators)."""
        self._dataset_shards = shards
        return True

    def run_train_fn(self, train_fn, config: dict, resume_path: str | None,
                     ckpt: dict | None = None) -> bool:
        import os

        resume = Checkpoint(resume_path) if resume_path else None
        ckpt = ckpt or {}
        async_mgr = None
        if ckpt.get("async_save") and self._context.world_rank == 0:
            # Rank 0 owns the async checkpoint stream (SPMD state is
            # replicated or reassembled by the train_fn; one writer keeps
            # commits linear). Root lives in run storage so checkpoints
            # outlive the worker — and the node.
            from ..resilience import AsyncCheckpointManager

            async_mgr = AsyncCheckpointManager(
                os.path.join(self._context.storage_path, "async_ckpts"),
                run_name=self._context.experiment_name,
                keep_k=ckpt.get("keep_k") or 2,
            )
        self._session = _Session(
            self._context, resume, dataset_shards=self._dataset_shards,
            async_ckpt=async_mgr,
            ckpt_every=int(ckpt.get("every_n_steps") or 1))
        self._error = None
        self._done = False

        def runner():
            _set_session(self._session)
            try:
                train_fn(config)
            except BaseException:
                self._error = traceback.format_exc()
            finally:
                if async_mgr is not None:
                    # A clean exit must not lose the tail checkpoint that
                    # is still in the writer queue.
                    try:
                        async_mgr.close(timeout=30.0)
                    except Exception:
                        pass
                self._done = True
                _set_session(None)

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        return True

    def poll(self) -> dict:
        reports = self._session.drain() if self._session else []
        return {"reports": reports, "done": self._done, "error": self._error}

    def shutdown(self) -> bool:
        return True


class LoopWorkerGroup:
    """Compiled-loop mode (round 15): instead of N SPMD closure-driven
    workers, the group is the THREE resident stage actors of
    ``train/loop.py`` — data-loader, train-step, checkpoint-snapshot —
    placed as one atomic unit so the controller's slice-atomic
    failure/restart discipline applies unchanged: any stage death tears
    the whole pipeline down and the next attempt resumes from the
    latest GCS-registered async checkpoint."""

    STAGE_NAMES = ("data", "step", "ckpt")

    def __init__(self, data, step, ckpt, pg):
        self.data = data
        self.step = step
        self.ckpt = ckpt
        self._pg = pg

    @classmethod
    def create(cls, scaling_config, experiment_name: str, storage_path: str,
               spec, config: dict, resume_path: str | None
               ) -> "LoopWorkerGroup":
        from .loop import CkptStage, DataLoaderStage, TrainStepStage

        # The step stage owns the devices (the trainer's worker
        # resources); loader + committer are host-side helpers.
        bundles = [{"CPU": 0.5}, dict(scaling_config.worker_resources()),
                   {"CPU": 0.5}]
        pg = placement_group(bundles,
                             strategy=scaling_config.placement_strategy)
        if not pg.wait(timeout_seconds=60.0):
            remove_placement_group(pg)
            raise TimeoutError(
                "placement group for the 3 train-loop stages not ready "
                "within 60s")

        def make(cls_, idx, name, *args):
            return ray.remote(cls_).options(
                resources=dict(bundles[idx]),
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=idx),
                name=f"train_loop_{experiment_name}_{name}",
                runtime_env=scaling_config.worker_runtime_env,
            ).remote(*args)

        data = make(DataLoaderStage, 0, "data", spec, config)
        step = make(TrainStepStage, 1, "step", spec, config, resume_path)
        ckpt = make(CkptStage, 2, "ckpt", spec, config, storage_path,
                    experiment_name)
        group = cls(data, step, ckpt, pg)
        try:
            # Readiness probe: constructor errors (bad init_fn, corrupt
            # resume checkpoint) surface HERE, as a group-creation
            # failure, not mid-loop.
            ray.get(step.start_step.remote(), timeout=120)
        except Exception:
            group.shutdown()
            raise
        return group

    @property
    def actors(self) -> list:
        return [self.data, self.step, self.ckpt]

    def shutdown(self) -> None:
        for a in self.actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass


class WorkerGroup:
    """Creates, polls and tears down the worker actors as one unit."""

    def __init__(self, workers, pg):
        self.workers = workers
        self._pg = pg
        self._splits: dict = {}

    @classmethod
    def create(cls, scaling_config, experiment_name: str, storage_path: str,
               num_workers: int | None = None) -> "WorkerGroup":
        n = num_workers if num_workers is not None else scaling_config.num_workers
        res = scaling_config.worker_resources()
        bundles = [dict(res) for _ in range(n)]
        if scaling_config.topology:
            # claim the slice head so the whole slice is ours atomically
            bundles[0][f"TPU-{scaling_config.topology}-head"] = 1.0
        pg = placement_group(bundles, strategy=scaling_config.placement_strategy)
        if not pg.wait(timeout_seconds=60.0):
            remove_placement_group(pg)
            raise TimeoutError(
                f"placement group for {n} train workers not ready within 60s"
            )
        actor_cls = ray.remote(TrainWorker)
        workers = [
            actor_cls.options(
                resources=dict(bundles[i]),
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=i
                ),
                name=f"train_worker_{experiment_name}_{i}",
                runtime_env=scaling_config.worker_runtime_env,
            ).remote(i, n, experiment_name, storage_path)
            for i in range(n)
        ]
        group = cls(workers, pg)
        if scaling_config.topology and n > 1:
            # Multi-host slice: bootstrap jax.distributed across the group.
            # Rank 0 resolves the coordinator endpoint; every worker joins
            # concurrently (initialize blocks until all processes arrive).
            coordinator = ray.get(workers[0].get_coordinator_address.remote(), timeout=60)
            ray.get([w.init_distributed.remote(coordinator) for w in workers], timeout=300)
        return group

    def setup_datasets(self, datasets: dict) -> None:
        """streaming_split each dataset across the group; worker i consumes
        split i. The split iterators are pinned on this group so their
        coordinator actors live exactly as long as the attempt."""
        if not datasets:
            return
        n = len(self.workers)
        self._splits = {name: ds.streaming_split(n) for name, ds in datasets.items()}
        refs = []
        for i, w in enumerate(self.workers):
            shards = {name: splits[i] for name, splits in self._splits.items()}
            refs.append(w.set_dataset_shards.remote(shards))
        ray.get(refs, timeout=120)

    def run_on_all(self, method: str, *args, timeout: float = 120.0):
        refs = [getattr(w, method).remote(*args) for w in self.workers]
        return ray.get(refs, timeout=timeout)

    def poll(self, timeout: float = 60.0) -> list[dict]:
        """Per-worker harvest: a dead worker yields an ``error`` entry
        instead of discarding the whole batch — reports already produced
        by surviving workers (rank-0 metrics + checkpoint registrations)
        must still reach the controller's ingest before the group failure
        is raised, or the attempt's progress is silently lost."""
        refs = [w.poll.remote() for w in self.workers]
        out = []
        for i, ref in enumerate(refs):
            try:
                out.append(ray.get(ref, timeout=timeout))
            except Exception as e:
                out.append({"error": f"worker {i} poll failed: {e}"})
        return out

    def shutdown(self) -> None:
        try:
            self.run_on_all("shutdown", timeout=10.0)
        except Exception:
            pass
        for w in self.workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        for splits in self._splits.values():
            for it in splits:
                try:
                    ray.kill(it._coord)
                except Exception:
                    pass
        self._splits = {}
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
