"""Checkpoints: directory handles + top-K retention.

Reference: ``python/ray/train/_checkpoint.py:56`` (Checkpoint),
``_internal/checkpoint_manager.py`` (top-K by metric). JAX pytrees are
saved with orbax when available (``save_pytree``/``load_pytree``), plain
directories otherwise.
"""

from __future__ import annotations

import contextlib
import heapq
import json
import os
import shutil
import tempfile
import uuid


class Checkpoint:
    """A handle to a checkpoint directory. Reference: _checkpoint.py:56."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: str | None = None) -> str:
        dest = dest or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self):
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_pytree(tree, path: str, *, name: str = "state") -> None:
    """Save a JAX pytree under ``path/name`` (orbax if present).

    The pickle fallback writes ATOMICALLY: a kill mid-save used to leave
    a truncated ``.pkl`` that unpickled a prefix of the tree without
    complaint — a corrupt, loadable-looking checkpoint. Now the bytes go
    to a same-directory temp file, are fsynced, and replace the target in
    one ``os.replace`` — a reader sees the previous complete version or
    none, never a partial one. (Orbax brings its own tmp+rename commit.)
    """
    os.makedirs(path, exist_ok=True)
    target = os.path.join(path, name)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(target, tree, force=True)
        ckptr.wait_until_finished()
    except ModuleNotFoundError:
        import pickle

        import jax

        final = target + ".pkl"
        tmp = final + f".tmp-{uuid.uuid4().hex[:8]}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(jax.device_get(tree), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise


def load_pytree(path: str, *, name: str = "state", like=None):
    """Load a pytree saved by ``save_pytree``. ``like`` restores sharding/
    dtype structure under orbax."""
    target = os.path.join(path, name)
    if os.path.isdir(target):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        if like is not None:
            return ckptr.restore(target, like)
        return ckptr.restore(target)
    import pickle

    with open(target + ".pkl", "rb") as f:
        return pickle.load(f)


class CheckpointManager:
    """Keeps the top-K reported checkpoints by a score attribute."""

    def __init__(self, config) -> None:
        self._config = config
        self._entries: list[tuple[float, int, Checkpoint]] = []  # (score, seq, ckpt)
        self._seq = 0
        self.latest: Checkpoint | None = None

    def register(self, checkpoint: Checkpoint, metrics: dict) -> None:
        self.latest = checkpoint
        attr = self._config.checkpoint_score_attribute
        keep = self._config.num_to_keep
        score = 0.0
        if attr is not None and attr in (metrics or {}):
            score = float(metrics[attr])
            if self._config.checkpoint_score_order == "min":
                score = -score
        self._seq += 1
        heapq.heappush(self._entries, (score, self._seq, checkpoint))
        meta = {"metrics": metrics or {}}
        try:
            with open(os.path.join(checkpoint.path, ".metrics.json"), "w") as f:
                json.dump(meta, f, default=str)
        except OSError:
            pass
        if keep is not None:
            while len(self._entries) > keep:
                _, _, evicted = heapq.heappop(self._entries)
                if evicted.path != checkpoint.path:
                    shutil.rmtree(evicted.path, ignore_errors=True)

    @property
    def best(self) -> Checkpoint | None:
        if not self._entries:
            return self.latest
        return max(self._entries)[2]
