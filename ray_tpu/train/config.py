"""Train configuration objects.

Reference: AIR ``python/ray/air/config.py`` (ScalingConfig:103,
FailureConfig:398, CheckpointConfig:448, RunConfig:597). TPU delta: a
worker is a *host* of a TPU slice, not a GPU; ``topology`` names the slice
type and the whole slice is the atomic scheduling/failure unit
(SURVEY.md §7.1/§7.3-4).
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    num_workers: SPMD processes (one per TPU host in a real slice).
    use_tpu: request TPU chip resources for each worker.
    topology: TPU slice type (e.g. "v5litepod-16"); when set, the worker
      group claims the matching ``TPU-{topology}-head`` resource so a slice
      is scheduled atomically (reference scheme: accelerators/tpu.py:70-192).
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: dict | None = None
    topology: str | None = None
    placement_strategy: str = "PACK"
    # Elastic training (reference v2 scaling_policy/scaling_policy.py:29):
    # when set, `num_workers` becomes the MAX and the controller sizes the
    # group to observed cluster capacity in [min_workers, num_workers],
    # restarting slice-atomically from the latest checkpoint on resize.
    min_workers: int | None = None
    # Per-worker runtime env ({"env_vars": {...}}). TPU idiom: the driver
    # stays off the chip (JAX_PLATFORMS=cpu) and the train workers claim it
    # by clearing that override.
    worker_runtime_env: dict | None = None

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker or {})
        if not res:
            res = {"CPU": 1.0}
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        return res


@dataclasses.dataclass
class FailureConfig:
    """max_failures: group-level restarts; -1 = unlimited. The whole worker
    group (slice) restarts together — per-worker restart is meaningless
    under SPMD (a dead host invalidates every peer's collectives)."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"
    # Async checkpointing (ray_tpu/resilience/checkpoint.py): rank 0
    # snapshots the ``state=`` pytree passed to ``train.report`` and
    # commits it from a background thread every ``every_n_steps`` reports
    # — the train step never blocks on I/O, commits are atomic (tmp dir +
    # commit marker + rename, keep-K via num_to_keep), and each committed
    # version registers with the GCS so recovery after node loss resolves
    # the latest checkpoint without touching the dead node.
    async_save: bool = False
    every_n_steps: int = 1


@dataclasses.dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    checkpoint_config: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)
    # Tune lifecycle callbacks (tune.Callback instances — e.g. the
    # bundled Json/CSV/TBX logger callbacks); ignored by bare Train runs.
    callbacks: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Result:
    """What ``fit()`` returns. Reference: ``ray/air/result.py``."""

    metrics: dict[str, Any] | None
    checkpoint: Any | None
    path: str | None
    error: Exception | None = None
    metrics_history: list[dict] = dataclasses.field(default_factory=list)
    # One entry per group restart (resilience): chaos-clock stamps of the
    # failure and of the first resumed report, plus the resume path — the
    # recovery bench derives `recovery_train_resume_s` from these.
    recovery_events: list[dict] = dataclasses.field(default_factory=list)
    # Compiled-loop mode only (train/loop.py): per-run drive statistics —
    # mode, per-step wall, checkpoint-commit windows and
    # `train_ckpt_overlap_frac` (fraction of checkpoint commit time that
    # overlapped step compute; the bench records it as a guarded cell).
    loop_stats: dict | None = None

    @property
    def best_checkpoints(self) -> list:
        return [self.checkpoint] if self.checkpoint else []
