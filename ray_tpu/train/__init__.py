"""ray_tpu.train: distributed training on TPU slices.

Reference: ``python/ray/train/`` v1+v2 (SURVEY.md §2.3, §3.4). The
controller-actor pattern is kept; NCCL process groups are replaced by
JAX SPMD — one worker per slice host, ``jax.distributed`` bootstrap,
parallelism via ``ray_tpu.parallel`` meshes inside the train_fn.
"""

from .checkpoint import Checkpoint, load_pytree, save_pytree
from .config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .controller import ElasticScalingPolicy, FixedScalingPolicy
from .loop import TrainLoopConfig, TrainLoopRunner
from .session import get_checkpoint, get_context, get_dataset_shard, report
from .trainer import DataParallelTrainer, JaxTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "ElasticScalingPolicy",
    "FixedScalingPolicy",
    "FailureConfig",
    "JaxTrainer",
    "TrainLoopConfig",
    "TrainLoopRunner",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "get_checkpoint",
    "get_dataset_shard",
    "get_context",
    "report",
    "load_pytree",
    "save_pytree",
]
