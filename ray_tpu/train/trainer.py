"""Trainers: the user-facing fit() entry points.

Reference: ``python/ray/train/v2/api/data_parallel_trainer.py:89``
(DataParallelTrainer.fit → TrainController) and
``v2/torch/torch_trainer.py:17``. The TPU-native flagship is
``JaxTrainer``: the train_fn runs as an SPMD program per host; inside it,
parallelism is expressed with ``ray_tpu.parallel`` meshes, not process
groups.
"""

from __future__ import annotations

from .checkpoint import Checkpoint
from .config import Result, RunConfig, ScalingConfig
from .controller import TrainController


class DataParallelTrainer:
    """Generic function trainer: N SPMD workers run ``train_loop_per_worker``.

    ``train_loop_per_worker`` is either the classic closure (eager,
    ``train.report()``-driven — the default path, unchanged) or a
    :class:`~ray_tpu.train.TrainLoopConfig` structured step spec (round
    15): data-loader → train-step → checkpoint-snapshot stage actors,
    driven eagerly (one dispatch chain per step) or — with
    ``use_compiled_loop=True`` — parked once on a persistent compiled
    loop (``dag/loop.py``) so steady-state steps are a channel
    write+read with zero per-step RPC/lease traffic and the async
    checkpoint commit overlaps the next step's compute. Both drives are
    byte-identical at a fixed seed.

    ``use_compiled_loop``: ``None`` (default) defers to
    ``TrainLoopConfig.use_compiled_loop``; ignored for closure specs.
    """

    def __init__(
        self,
        train_loop_per_worker,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        resume_from_checkpoint: Checkpoint | None = None,
        datasets: dict | None = None,
        scaling_policy=None,
        use_compiled_loop: bool | None = None,
    ):
        self._train_fn = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self._scaling_config = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._resume = resume_from_checkpoint
        self._datasets = datasets or {}
        self._scaling_policy = scaling_policy
        self._use_compiled_loop = use_compiled_loop

    def fit(self) -> Result:
        controller = TrainController(
            self._train_fn,
            train_loop_config=self._train_loop_config,
            scaling_config=self._scaling_config,
            run_config=self._run_config,
            resume_from_checkpoint=self._resume,
            datasets=self._datasets,
            scaling_policy=self._scaling_policy,
            use_compiled_loop=self._use_compiled_loop,
        )
        return controller.run()


class JaxTrainer(DataParallelTrainer):
    """The flagship TPU trainer (replaces the reference's TorchTrainer).

    Each worker hosts one JAX process; ``init_distributed`` wires
    ``jax.distributed`` for multi-host slices. Model/optimizer sharding is
    the train_fn's business via ``ray_tpu.parallel``.

    Spot-slice resilience: with ``CheckpointConfig(async_save=True,
    every_n_steps=N)`` the train_fn passes its state pytree to
    ``train.report(metrics, state=...)`` — rank 0 commits it atomically
    from a background thread and registers each version with the GCS, so
    a preempted slice restarts from the latest committed step
    (``ray_tpu/resilience/``; recovery SLOs in ``cli bench recovery``).
    """
