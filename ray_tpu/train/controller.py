"""TrainController: the driver-side control loop.

Reference: ``python/ray/train/v2/_internal/execution/controller/
controller.py:91`` (run:453, loop:430) with pluggable ScalingPolicy
(``execution/scaling_policy/``) and FailurePolicy
(``execution/failure_handling/``). TPU delta (SURVEY.md §7.3-4): the
worker group (slice) is the atomic failure unit — any worker failure
tears the whole group down and restarts it from the latest checkpoint.
"""

from __future__ import annotations

import logging
import time

from ..chaos import clock as chaos_clock
from .checkpoint import Checkpoint, CheckpointManager
from .config import Result, RunConfig, ScalingConfig
from .loop import TrainLoopConfig, TrainLoopRunner
from .worker_group import LoopWorkerGroup, WorkerGroup

logger = logging.getLogger(__name__)


class FixedScalingPolicy:
    """Reference: execution/scaling_policy/fixed.py."""

    def __init__(self, scaling_config: ScalingConfig):
        self._config = scaling_config

    def group_size(self, current: int | None = None) -> int:
        return self._config.num_workers

    def monitor(self, current: int) -> int | None:
        return None  # never resizes


class ElasticScalingPolicy:
    """Size the group to observed cluster capacity within
    ``[min_workers, num_workers]`` (reference v2
    ``execution/scaling_policy/scaling_policy.py:29`` ResizeDecision).

    TPU discipline: the worker group is slice-atomic, so a resize is a
    whole-group restart from the latest checkpoint — never an in-place
    membership change (SPMD collectives can't survive one)."""

    def __init__(self, scaling_config: ScalingConfig, *,
                 check_interval_s: float = 2.0, clock=None):
        self._config = scaling_config
        self.min = max(1, scaling_config.min_workers or 1)
        self.max = scaling_config.num_workers
        self._check_interval = check_interval_s
        # Injectable clock so the debounce is testable without wall-time
        # sleeps (load-sensitive timing was a full-suite flake source).
        # Default: the chaos clock (wall time unless a VirtualClock is
        # installed — chaos/clock.py), generalizing the PR-1 fake clock.
        if clock is None:
            from ..chaos import clock as chaos_clock

            clock = chaos_clock.now
        self._clock = clock
        self._next_check = 0.0
        self._pending_target: int | None = None

    def _feasible_workers(self, holding: int = 0) -> int:
        """Workers the cluster can host NOW: floor over each required
        resource of available/required, plus what the current group holds."""
        from ..core import api as ray

        need = self._config.worker_resources()
        try:
            avail = ray.available_resources()
        except Exception:
            return holding or self.min
        fits = min(
            int(avail.get(res, 0.0) / amount) for res, amount in need.items()
        ) if need else self.max
        return max(0, fits) + holding

    def group_size(self, current: int | None = None) -> int:
        feasible = self._feasible_workers(holding=current or 0)
        size = max(self.min, min(self.max, feasible))
        return size

    def monitor(self, current: int) -> int | None:
        """While the group runs: return a new size when capacity changed
        enough to justify a slice-atomic restart, else None. Debounced:
        the target must hold for two consecutive checks — node-death
        detection lags heartbeats, and a dying node's resources would
        otherwise read as phantom upscale capacity."""
        now = self._clock()
        if now < self._next_check:
            return None
        self._next_check = now + self._check_interval
        target = max(self.min, min(self.max, self._feasible_workers(holding=current)))
        if target == current:
            self._pending_target = None
            return None
        if target == self._pending_target:
            self._pending_target = None
            return target
        self._pending_target = target
        return None


class _ResizeSignal(Exception):
    def __init__(self, new_size: int):
        super().__init__(f"resize to {new_size}")
        self.new_size = new_size


class MaxFailurePolicy:
    """Restart the whole group up to max_failures times (-1 = unlimited)."""

    def __init__(self, max_failures: int):
        self._max = max_failures
        self.failures = 0

    def should_restart(self) -> bool:
        self.failures += 1
        return self._max == -1 or self.failures <= self._max


class WorkerGroupError(RuntimeError):
    pass


class TrainController:
    def __init__(
        self,
        train_fn,
        *,
        train_loop_config: dict | None,
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        resume_from_checkpoint: Checkpoint | None = None,
        poll_interval_s: float = 0.2,
        datasets: dict | None = None,
        scaling_policy=None,
        use_compiled_loop: bool | None = None,
    ):
        self._train_fn = train_fn
        # Structured-step mode (round 15): a TrainLoopConfig instead of a
        # closure routes the attempt through the stage-actor pipeline —
        # eager per-step dispatch or the persistent compiled loop.
        self._loop_spec = train_fn if isinstance(train_fn, TrainLoopConfig) \
            else None
        self._use_compiled_loop = use_compiled_loop
        self.loop_stats: dict | None = None
        self._config = train_loop_config or {}
        self._datasets = datasets or {}
        self._scaling = scaling_config
        self._run_config = run_config
        # scaling_policy overrides the config-derived default — tests
        # inject an ElasticScalingPolicy with a fake clock so the resize
        # debounce is call-count-driven, not wall-clock-sensitive.
        self._scaling_policy = scaling_policy or (
            ElasticScalingPolicy(scaling_config)
            if scaling_config.min_workers is not None
            else FixedScalingPolicy(scaling_config)
        )
        self._failure_policy = MaxFailurePolicy(run_config.failure_config.max_failures)
        self._ckpt_manager = CheckpointManager(run_config.checkpoint_config)
        self._resume = resume_from_checkpoint
        self._poll_interval = poll_interval_s
        self._metrics_history: list[dict] = []
        self._experiment_name: str = ""
        # Recovery accounting (resilience subsystem): one entry per
        # group restart, chaos-clock stamped at the failure and at the
        # first report of the resumed attempt — the recovery bench and
        # tests derive `recovery_train_resume_s` from these.
        self.recovery_events: list[dict] = []
        self._pending_recovery: dict | None = None

    def run(self) -> Result:
        import os

        name = self._run_config.name or f"train_{int(time.time())}"
        self._experiment_name = name
        storage = self._run_config.storage_path or "/tmp/ray_tpu/results"
        run_dir = os.path.join(storage, name)
        os.makedirs(run_dir, exist_ok=True)

        last_error: Exception | None = None
        size = self._scaling_policy.group_size()
        while True:
            group = None
            try:
                # Group creation can fail too (e.g. the placement group is
                # unschedulable because a node died and the size is stale):
                # route it through the same failure/re-size path.
                if self._loop_spec is not None:
                    # Structured-step mode: the group is the 3 resident
                    # stage actors; the step stage loads the resume
                    # checkpoint at construction.
                    resume = self._resolve_resume()
                    try:
                        group = LoopWorkerGroup.create(
                            self._scaling, name, run_dir, self._loop_spec,
                            self._config,
                            resume.path if resume else None)
                    except Exception as e:
                        raise WorkerGroupError(
                            f"train-loop stage creation failed: {e}") from e
                    self._run_attempt_loop(group)
                    break
                try:
                    group = WorkerGroup.create(
                        self._scaling, name, run_dir, num_workers=size)
                except Exception as e:
                    raise WorkerGroupError(f"worker group creation failed: {e}") from e
                # Fresh streaming splits per attempt: a restarted group must
                # not consume a dead attempt's half-drained stream.
                group.setup_datasets(self._datasets)
                self._run_attempt(group, size)
                break
            except _ResizeSignal as rs:
                # Not a failure: slice-atomic restart at the new size from
                # the latest checkpoint (reference ResizeDecision handling).
                logger.info("Elastic resize: %d -> %d workers (restarting from "
                            "latest checkpoint)", size, rs.new_size)
                size = rs.new_size
                continue
            except WorkerGroupError as e:
                last_error = e
                if self._failure_policy.should_restart():
                    resume = self._resolve_resume()
                    self._pending_recovery = {
                        "failed_clock": chaos_clock.now(),
                        "attempt": self._failure_policy.failures,
                        "resume_path": resume.path if resume else None,
                        "resumed_clock": None,
                    }
                    self.recovery_events.append(self._pending_recovery)
                    logger.warning(
                        "Worker group failed (attempt %d); restarting whole "
                        "group from %s: %s",
                        self._failure_policy.failures, resume, e,
                    )
                    # Re-size on restart: a lost node may have shrunk the
                    # feasible group (elastic policies adapt, fixed repeats).
                    size = self._scaling_policy.group_size(current=0)
                    continue
                return Result(
                    metrics=self._metrics_history[-1] if self._metrics_history else None,
                    checkpoint=self._final_checkpoint(),
                    path=run_dir,
                    error=last_error,
                    metrics_history=self._metrics_history,
                    recovery_events=self.recovery_events,
                    loop_stats=self.loop_stats,
                )
            finally:
                if group is not None:
                    group.shutdown()

        return Result(
            metrics=self._metrics_history[-1] if self._metrics_history else None,
            checkpoint=self._final_checkpoint(),
            path=run_dir,
            error=None,
            metrics_history=self._metrics_history,
            recovery_events=self.recovery_events,
            loop_stats=self.loop_stats,
        )

    # ------------------------------------------------------------------
    def _resolve_resume(self) -> Checkpoint | None:
        """The checkpoint the next attempt resumes from. With async_save,
        the GCS-registered latest committed version wins — it is found
        through the control plane, so a dead worker node cannot hide it;
        the report()-registered manager is the sync-mode fallback."""
        ckpt_cfg = self._run_config.checkpoint_config
        loop_snapshots = (self._loop_spec is not None
                          and self._loop_spec.snapshot_every > 0)
        if (getattr(ckpt_cfg, "async_save", False) or loop_snapshots) \
                and self._experiment_name:
            try:
                from ..resilience import latest_registered

                entry = latest_registered(self._experiment_name)
            except Exception:
                entry = None
            if entry is not None:
                return Checkpoint(entry["path"])
        return self._ckpt_manager.latest or self._resume

    def _run_attempt_loop(self, group: LoopWorkerGroup) -> None:
        """One attempt of the structured-step pipeline. Entries flow
        through the SAME ingest as closure-mode reports (identical
        ``metrics_history``/recovery-stamp shape); any stage failure —
        creation, mid-loop death, channel teardown — maps onto
        ``WorkerGroupError`` so the controller's failure policy and
        checkpoint-resume path apply unchanged."""
        runner = TrainLoopRunner(group, self._loop_spec,
                                 use_compiled_loop=self._use_compiled_loop)

        def on_report(entry: dict) -> None:
            report = {"rank": 0,
                      "metrics": dict(entry.get("metrics") or {})}
            if "ckpt_save_block_ms" in entry:
                report["ckpt_save_block_ms"] = entry["ckpt_save_block_ms"]
            self._ingest([{"reports": [report]}])

        try:
            self.loop_stats = runner.run(on_report)
        except Exception as e:
            raise WorkerGroupError(f"train loop attempt failed: {e}") from e

    def _final_checkpoint(self):
        """Result.checkpoint: loop-mode runs resolve the latest
        GCS-registered async commit; closure mode keeps the manager."""
        if self._loop_spec is not None and self._loop_spec.snapshot_every:
            try:
                from ..resilience import latest_registered

                entry = latest_registered(self._experiment_name)
                if entry is not None:
                    return Checkpoint(entry["path"])
            except Exception:
                pass
        return self._ckpt_manager.best

    def _run_attempt(self, group: WorkerGroup, size: int) -> None:
        resume = self._resolve_resume()
        resume_path = resume.path if resume else None
        ckpt_cfg = self._run_config.checkpoint_config
        ckpt_meta = {
            "async_save": getattr(ckpt_cfg, "async_save", False),
            "every_n_steps": getattr(ckpt_cfg, "every_n_steps", 1),
            "keep_k": ckpt_cfg.num_to_keep,
        }
        try:
            group.run_on_all("run_train_fn", self._train_fn, self._config,
                             resume_path, ckpt_meta)
        except Exception as e:
            raise WorkerGroupError(f"failed to start train_fn: {e}") from e

        while True:
            try:
                polls = group.poll()
            except Exception as e:
                raise WorkerGroupError(f"lost contact with worker group: {e}") from e
            self._ingest(polls)
            for i, p in enumerate(polls):
                if p.get("error"):
                    raise WorkerGroupError(f"worker {i} failed:\n{p['error']}")
            if all(p.get("done") for p in polls):
                return
            new_size = self._scaling_policy.monitor(size)
            if new_size is not None:
                raise _ResizeSignal(new_size)
            time.sleep(self._poll_interval)

    def _ingest(self, polls: list[dict]) -> None:
        for p in polls:
            for entry in p.get("reports", []):
                if entry["rank"] == 0:
                    metrics = entry["metrics"]
                    if self._pending_recovery is not None:
                        # First report after a restart: the run has
                        # resumed — this stamp closes the recovery window.
                        self._pending_recovery["resumed_clock"] = chaos_clock.now()
                        self._pending_recovery["resume_step"] = metrics.get("step")
                        self._pending_recovery = None
                    self._metrics_history.append(metrics)
                    if "checkpoint_path" in entry:
                        self._ckpt_manager.register(
                            Checkpoint(entry["checkpoint_path"]), metrics
                        )
