"""Worker-side training session: ``report()`` and ``get_context()``.

Reference: ``python/ray/train/_internal/session.py:112,405,672``
(_TrainSession.report) and v2 ``train_fn_utils.py:13``. The session lives
inside each TrainWorker actor process; ``report`` enqueues metrics (and an
optional checkpoint directory) for the controller's next poll.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import uuid
from typing import Any

from .checkpoint import Checkpoint


@dataclasses.dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    storage_path: str

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _Session:
    def __init__(self, context: TrainContext, resume_checkpoint: Checkpoint | None,
                 dataset_shards: dict | None = None):
        self.context = context
        self.resume_checkpoint = resume_checkpoint
        self.dataset_shards = dataset_shards or {}
        self._lock = threading.Lock()
        self._reports: list[dict] = []
        self._step = 0

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None) -> None:
        entry: dict[str, Any] = {"metrics": dict(metrics or {}), "rank": self.context.world_rank}
        if checkpoint is not None:
            # persist into run storage so it outlives the worker's tmpdir
            dest = os.path.join(
                self.context.storage_path,
                f"checkpoint_{self._step:06d}_{uuid.uuid4().hex[:6]}",
            )
            if os.path.abspath(checkpoint.path) != dest:
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            entry["checkpoint_path"] = dest
        self._step += 1
        with self._lock:
            self._reports.append(entry)

    def drain(self) -> list[dict]:
        with self._lock:
            out, self._reports = self._reports, []
        return out


_session: _Session | None = None


def _set_session(s: _Session | None) -> None:
    global _session
    _session = s


def _get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "No training session active — report()/get_context() are only "
            "valid inside a train_fn launched by a Trainer"
        )
    return _session


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """Report metrics (+ optional checkpoint) from the train loop.
    Reference: v2/api/train_fn_utils.py:13."""
    _get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    """Reference: ray.train.get_context()."""
    return _get_session().context


def get_checkpoint() -> Checkpoint | None:
    """Checkpoint to resume from, if the controller restored one."""
    return _get_session().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    """This rank's streaming DataIterator for the named dataset passed to
    the Trainer (reference: ``ray.train.get_dataset_shard``,
    ``python/ray/train/_internal/session.py:672``)."""
    shard = _get_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"No dataset {name!r} was passed to the Trainer "
            f"(available: {sorted(_get_session().dataset_shards)})"
        )
    return shard
