"""Worker-side training session: ``report()`` and ``get_context()``.

Reference: ``python/ray/train/_internal/session.py:112,405,672``
(_TrainSession.report) and v2 ``train_fn_utils.py:13``. The session lives
inside each TrainWorker actor process; ``report`` enqueues metrics (and an
optional checkpoint directory) for the controller's next poll.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import time
import uuid
from typing import Any

from .checkpoint import Checkpoint

# Per-step training gauges pushed through the metrics pipeline from each
# worker's report() (reference: ray.train step metrics on the dashboard).
_metrics_lock = threading.Lock()
_metrics: dict = {}

# report() keys mapped onto the exported tokens/s gauge, first match wins.
_TOKENS_KEYS = ("tokens_per_s", "tokens_per_sec", "tokens_per_sec_per_chip")


def _train_metrics() -> dict:
    with _metrics_lock:
        if not _metrics:
            from ..util.metrics import Gauge

            tags = ("experiment", "rank")
            _metrics["step_time"] = Gauge(
                "train_step_time_s", "Wall time between report() calls",
                tag_keys=tags)
            _metrics["tokens_per_s"] = Gauge(
                "train_tokens_per_s", "Reported training token throughput",
                tag_keys=tags)
            _metrics["mfu"] = Gauge(
                "train_mfu", "Reported model FLOPs utilization", tag_keys=tags)
        return _metrics


@dataclasses.dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    storage_path: str

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _Session:
    def __init__(self, context: TrainContext, resume_checkpoint: Checkpoint | None,
                 dataset_shards: dict | None = None, async_ckpt=None,
                 ckpt_every: int = 1):
        self.context = context
        self.resume_checkpoint = resume_checkpoint
        self.dataset_shards = dataset_shards or {}
        self._lock = threading.Lock()
        self._reports: list[dict] = []
        self._step = 0
        self._last_report_t: float | None = None
        # Async checkpointing (resilience subsystem): rank 0 holds the
        # manager; report(state=...) snapshots + background-commits every
        # `ckpt_every` reports without blocking the train step.
        self._async_ckpt = async_ckpt
        self._ckpt_every = max(1, int(ckpt_every or 1))

    def _export_step_metrics(self, metrics: dict) -> None:
        """Per-step gauges (step_time_s / tokens_per_s / mfu) so training
        progress is visible on the metrics/Grafana path, not only in the
        controller's result log. Never raises into the train loop."""
        try:
            tags = {"experiment": self.context.experiment_name,
                    "rank": str(self.context.world_rank)}
            m = _train_metrics()
            now = time.monotonic()
            if self._last_report_t is not None:
                m["step_time"].set(now - self._last_report_t, tags)
            self._last_report_t = now
            for key in _TOKENS_KEYS:
                if key in metrics:
                    m["tokens_per_s"].set(float(metrics[key]), tags)
                    break
            if "mfu" in metrics:
                m["mfu"].set(float(metrics["mfu"]), tags)
        except Exception:
            pass

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None,
               state=None) -> None:
        entry: dict[str, Any] = {"metrics": dict(metrics or {}), "rank": self.context.world_rank}
        self._export_step_metrics(entry["metrics"])
        if state is not None and self._async_ckpt is not None:
            if self._step % self._ckpt_every == 0:
                block_ms = self._async_ckpt.save(
                    self._step, state, metrics=entry["metrics"])
                entry["ckpt_save_block_ms"] = round(block_ms, 3)
        if checkpoint is not None:
            # persist into run storage so it outlives the worker's tmpdir
            dest = os.path.join(
                self.context.storage_path,
                f"checkpoint_{self._step:06d}_{uuid.uuid4().hex[:6]}",
            )
            if os.path.abspath(checkpoint.path) != dest:
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            entry["checkpoint_path"] = dest
        self._step += 1
        with self._lock:
            self._reports.append(entry)

    def drain(self) -> list[dict]:
        with self._lock:
            out, self._reports = self._reports, []
        return out


_session: _Session | None = None


def _set_session(s: _Session | None) -> None:
    global _session
    _session = s


def _get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "No training session active — report()/get_context() are only "
            "valid inside a train_fn launched by a Trainer"
        )
    return _session


def report(metrics: dict, checkpoint: Checkpoint | None = None,
           state=None) -> None:
    """Report metrics (+ optional checkpoint) from the train loop.
    Reference: v2/api/train_fn_utils.py:13.

    With ``CheckpointConfig(async_save=True, every_n_steps=N)``, pass the
    train-state pytree as ``state=`` — rank 0 snapshots it and commits a
    checkpoint from a background thread every N reports (atomic commit +
    GCS registration; the step never blocks on I/O). Put everything
    recovery needs inside the tree: parameters, the step counter, the
    data-iterator position."""
    _get_session().report(metrics, checkpoint, state=state)


def get_context() -> TrainContext:
    """Reference: ray.train.get_context()."""
    return _get_session().context


def get_checkpoint() -> Checkpoint | None:
    """Checkpoint to resume from, if the controller restored one."""
    return _get_session().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    """This rank's streaming DataIterator for the named dataset passed to
    the Trainer (reference: ``ray.train.get_dataset_shard``,
    ``python/ray/train/_internal/session.py:672``)."""
    shard = _get_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"No dataset {name!r} was passed to the Trainer "
            f"(available: {sorted(_get_session().dataset_shards)})"
        )
    return shard
