"""Compiled-loop training: train steps ride the persistent graph.

The PR-8 persistent-graph runtime (``dag/loop.py``) killed the per-tick
dispatch cost of the pp *serve* engine (3,189 → 281 µs on the sandbox);
this module brings the same treatment to Train. A structured step spec
(:class:`TrainLoopConfig`) is parked as THREE resident tick executors —

    data-loader  →  train-step  →  checkpoint-snapshot

— streaming over credit-based ring channels, so a steady-state training
step is one channel write + one channel read with ZERO per-step task
submission, RPC, or lease traffic, and the PR-9
``AsyncCheckpointManager`` host snapshot commits in its OWN stage,
overlapped with the next step's compute instead of serialized against
it (measured as ``train_ckpt_overlap_frac``).

Both drive modes run the SAME stage actors in the SAME order, so they
are byte-identical at a fixed seed (the parity contract tests assert):

  * **eager** (the default fallback, and the measured baseline): one
    dynamically-dispatched ``.remote()`` chain per step — the
    submit→lease→push path every iteration, exactly like the dag
    bench's "dynamic" cell.
  * **compiled loop** (``use_compiled_loop=True``): ``compile_loop``
    parks the stages once; afterwards ``put(step)`` / ``get()`` stream
    over the rings with up to ``credits`` steps in flight.

The classic ``train_fn`` + ``train.report()`` API is untouched — eager
closure-driven training stays the default; the loop mode is opt-in via
``DataParallelTrainer(TrainLoopConfig(...), use_compiled_loop=True)``.
``train.report`` keeps its exact signature; loop-mode step metrics reach
the controller through the same ingest path (``Result.metrics_history``
is shaped identically).

Failure story: a stage death (chaos ``kill_loop_stage``, preemption)
surfaces on a bounded ``get()``, the loop tears down within the
dag-loop cascade bounds, and the controller's normal failure policy
restarts the attempt from the latest GCS-registered async checkpoint —
``recovery_ckpt_lag_steps`` is bounded by ``snapshot_every``.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

from ..core import api as ray


@dataclasses.dataclass
class TrainLoopConfig:
    """Structured step spec for compiled-loop (and eager-driven) training.

    step_fn:  ``(state, batch) -> (state, metrics)`` — one training step.
              Runs inside the train-step stage actor; the state pytree
              never leaves it except as checkpoint snapshots.
    init_fn:  ``(config: dict) -> state`` — build (or re-build) the
              initial state. On a restart the resumed checkpoint tree
              overwrites it (``load_checkpoint(like=init_fn(config))``).
    num_steps: total steps for the run (global — a resumed attempt
              continues from the checkpointed step).
    data_fn:  ``(config) -> iterable`` yielding one batch per step in the
              data-loader stage; ``None`` feeds the bare step index
              (steps that synthesize their own batch). Must be
              deterministic for the loop-vs-eager parity contract.
    snapshot_every: every N completed steps the train-step stage emits a
              HOST snapshot downstream and the checkpoint stage commits
              it atomically + registers it with the GCS
              (``resilience.AsyncCheckpointManager``); 0 disables
              checkpointing entirely.
    use_compiled_loop: default drive mode (the trainer's
              ``use_compiled_loop=`` overrides it).
    credits:  max steps in flight through the rings (pipelining depth —
              this is what lets checkpoint commits overlap compute).
    channel_capacity: per-message byte bound for the rings; must hold a
              pickled host snapshot when ``snapshot_every`` > 0.
    keep_k:   committed checkpoint versions retained (keep-K GC).
    stage_init_hook: ``(stage_name, config) -> None`` run in each stage
              actor's constructor (``stage_name`` ∈ {"data", "step",
              "ckpt"}) — the injection seam chaos tests use to install a
              ``kill_loop_stage`` FaultPlan inside the train-step stage.
    """

    step_fn: Callable
    init_fn: Callable
    num_steps: int
    data_fn: Callable | None = None
    snapshot_every: int = 0
    use_compiled_loop: bool = True
    credits: int = 4
    channel_capacity: int = 4 << 20
    keep_k: int = 2
    stage_init_hook: Callable | None = None


def _block_on(tree) -> None:
    """Wait for any in-flight device computation in ``tree`` — step/wall
    windows must measure compute, not dispatch."""
    try:
        import jax

        jax.block_until_ready(tree)
    except Exception:
        pass


def _host_snapshot(tree):
    from ..resilience.checkpoint import _snapshot

    return _snapshot(tree)


class DataLoaderStage:
    """Resident data-loader: tick ``i`` emits ``(i, batch_i)``."""

    def __init__(self, spec: TrainLoopConfig, config: dict):
        if spec.stage_init_hook is not None:
            spec.stage_init_hook("data", config)
        self._it = iter(spec.data_fn(config)) if spec.data_fn else None

    def next_batch(self, i: int):
        return (i, next(self._it) if self._it is not None else i)


class TrainStepStage:
    """Resident train step: holds the state pytree; tick ``(i, batch)``
    runs ``step_fn`` and — every ``snapshot_every`` steps — attaches a
    host snapshot for the downstream checkpoint stage."""

    def __init__(self, spec: TrainLoopConfig, config: dict,
                 resume_path: str | None):
        if spec.stage_init_hook is not None:
            spec.stage_init_hook("step", config)
        self._spec = spec
        self._state = spec.init_fn(config)
        self._start = 0
        if resume_path:
            from ..resilience.checkpoint import load_checkpoint

            tree, meta = load_checkpoint(resume_path, like=self._state)
            self._state = tree
            self._start = int(meta.get("step", -1)) + 1

    def start_step(self) -> int:
        """First step this attempt runs (0, or resumed-step + 1)."""
        return self._start

    def train_step(self, msg):
        i, batch = msg
        t0 = time.time()
        self._state, metrics = self._spec.step_fn(self._state, batch)
        _block_on(self._state)
        t1 = time.time()
        out = {"step": i, "metrics": dict(metrics or {}),
               "step_window": (t0, t1)}
        every = self._spec.snapshot_every
        if every and (i + 1) % every == 0:
            s0 = time.time()
            out["snapshot"] = _host_snapshot(self._state)
            out["snapshot_ms"] = round((time.time() - s0) * 1e3, 3)
        return out

    def state_snapshot(self):
        """Host copy of the current state (parity tests / final fetch)."""
        return _host_snapshot(self._state)


class CkptStage:
    """Resident checkpoint committer: ticks WITHOUT a snapshot pass
    through untouched; ticks WITH one ride the PR-9 atomic commit path
    (tmp + fsync + COMMITTED marker + rename, GCS-registered) while the
    train-step stage — a different process, ``credits`` ticks ahead —
    keeps computing. The commit WINDOW is stamped so the driver can
    measure how much of it overlapped step compute."""

    def __init__(self, spec: TrainLoopConfig, config: dict,
                 storage_path: str, run_name: str):
        if spec.stage_init_hook is not None:
            spec.stage_init_hook("ckpt", config)
        self._mgr = None
        if spec.snapshot_every:
            from ..resilience import AsyncCheckpointManager

            self._mgr = AsyncCheckpointManager(
                os.path.join(storage_path, "async_ckpts"),
                run_name=run_name, keep_k=spec.keep_k)

    def commit(self, out: dict) -> dict:
        snap = out.pop("snapshot", None)
        if snap is not None and self._mgr is not None:
            t0 = time.time()
            block_ms = self._mgr.save(out["step"], snap,
                                      metrics=out["metrics"])
            # Waiting here is FREE parallelism: this stage's tick blocks,
            # the step stage does not — that concurrency is the whole
            # point of giving the commit its own stage.
            self._mgr.wait(timeout=300.0)
            out["ckpt_window"] = (t0, time.time())
            out["ckpt_save_block_ms"] = round(block_ms, 3)
        return out


def _overlap_s(window: tuple, others: list[tuple]) -> float:
    s0, e0 = window
    total = 0.0
    for s1, e1 in others:
        total += max(0.0, min(e0, e1) - max(s0, s1))
    return total


class TrainLoopRunner:
    """Drives the three stages start→num_steps in either mode and folds
    the per-step entries into overlap/dispatch statistics."""

    def __init__(self, group, spec: TrainLoopConfig,
                 use_compiled_loop: bool | None = None):
        self._group = group
        self._spec = spec
        self.use_compiled_loop = (spec.use_compiled_loop
                                  if use_compiled_loop is None
                                  else use_compiled_loop)
        self.stats: dict[str, Any] = {}

    # ------------------------------------------------------------------
    def run(self, on_report: Callable[[dict], None]) -> dict:
        g = self._group
        start = ray.get(g.step.start_step.remote(), timeout=120)
        total = max(0, self._spec.num_steps - start)
        step_windows: list[tuple] = []
        ckpt_windows: list[tuple] = []
        save_block_ms = 0.0

        def handle(entry: dict) -> None:
            nonlocal save_block_ms
            step_windows.append(tuple(entry.get("step_window", (0.0, 0.0))))
            if "ckpt_window" in entry:
                ckpt_windows.append(tuple(entry["ckpt_window"]))
                save_block_ms = max(save_block_ms,
                                    entry.get("ckpt_save_block_ms", 0.0))
            on_report(entry)

        t_run0 = time.perf_counter()
        if total:
            if self.use_compiled_loop:
                self._run_loop(start, total, handle)
            else:
                self._run_eager(start, total, handle)
        wall = time.perf_counter() - t_run0

        overlap = sum(_overlap_s(w, step_windows) for w in ckpt_windows)
        ckpt_total = sum(e - s for s, e in ckpt_windows)
        # Steady-state window: end of step 0 → end of the last step.
        # Excludes the first step's jit compile and the loop's one-time
        # channel/park setup, so per-step numbers measure the DRIVE, not
        # warmup (the bench's dispatch-overhead and MFU cells use this).
        steady_steps = max(0, len(step_windows) - 1)
        steady_wall = (step_windows[-1][1] - step_windows[0][1]
                       if steady_steps else 0.0)
        self.stats = {
            "mode": "loop" if self.use_compiled_loop else "eager",
            "steps": total,
            "start_step": start,
            "wall_s": round(wall, 4),
            "step_wall_us": round(wall / total * 1e6, 1) if total else 0.0,
            "steady_steps": steady_steps,
            "steady_wall_s": round(steady_wall, 4),
            "steady_step_wall_us": (
                round(steady_wall / steady_steps * 1e6, 1)
                if steady_steps else 0.0),
            "step_compute_s": round(
                sum(e - s for s, e in step_windows), 4),
            "ckpt_commits": len(ckpt_windows),
            "ckpt_total_s": round(ckpt_total, 4),
            "ckpt_save_block_ms": round(save_block_ms, 3),
            "train_ckpt_overlap_frac": (
                round(overlap / ckpt_total, 4) if ckpt_total > 0 else None),
        }
        if getattr(self, "_torn_down_in_s", None) is not None:
            self.stats["loop_torn_down_in_s"] = round(self._torn_down_in_s, 4)
        loop_stats = getattr(self, "_loop_stats", None)
        if loop_stats:
            self.stats["loop_stall"] = {
                "bottleneck": loop_stats.get("bottleneck"),
                "stages": {
                    name: {"ticks": st.get("ticks", 0),
                           "state": st.get("state"),
                           "frac": st.get("frac")}
                    for name, st in (loop_stats.get("stages") or {}).items()
                },
            }
        return self.stats

    # ------------------------------------------------------------------
    def _run_eager(self, start: int, total: int, handle) -> None:
        """Dynamic per-step dispatch — the dag bench's "dynamic" cell
        shape: one ``.remote()`` chain + one ``get`` per step, paying
        the full submit→lease→push path every iteration, with the
        checkpoint commit serialized against the next step."""
        g = self._group
        for i in range(start, start + total):
            entry = ray.get(
                g.ckpt.commit.remote(
                    g.step.train_step.remote(
                        g.data.next_batch.remote(i))),
                timeout=600)
            handle(entry)

    def _run_loop(self, start: int, total: int, handle) -> None:
        """Compiled-loop drive: park the stages once, then stream —
        ``put`` is a ring write, results drain in order ``credits``
        deep behind, and the parked checkpoint stage commits while the
        step stage computes ahead of it."""
        from ..dag import InputNode, compile_loop

        g = self._group
        with InputNode() as inp:
            out = g.ckpt.commit.bind(
                g.step.train_step.bind(
                    g.data.next_batch.bind(inp)))
        loop = compile_loop(out, max_buffer_size=self._spec.channel_capacity,
                            credits=self._spec.credits)
        got = 0
        try:
            for i in range(start, start + total):
                loop.put(i, timeout=300.0)
                while loop.in_flight >= loop.credits:
                    handle(loop.get(timeout=300.0))
                    got += 1
            while got < total:
                handle(loop.get(timeout=300.0))
                got += 1
        finally:
            loop.teardown()
            # Stall attribution of the drive: which of data/step/ckpt
            # the loop actually waited on. Teardown captures it after
            # the stages' final flush, before the snapshot files vanish.
            self._loop_stats = getattr(loop, "final_stats", None)
            self._torn_down_in_s = getattr(loop, "torn_down_in_s", None)

    # ------------------------------------------------------------------
    def final_state(self):
        """Host copy of the step stage's final state. Valid after
        ``run()`` returned (the loop is torn down; the actor is idle)."""
        return ray.get(self._group.step.state_snapshot.remote(), timeout=300)
