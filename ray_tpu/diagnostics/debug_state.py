"""debug_state.txt rendering + atomic writes.

Reference: the raylet's periodic ``DumpDebugState`` →
``<session>/logs/debug_state.txt`` (``src/ray/raylet/node_manager.cc``
RecordMetrics/DebugString). Snapshots are plain nested dicts; this module
renders them as the familiar indented key: value text and writes them
atomically so a reader never sees a torn file.
"""

from __future__ import annotations

import os
import time


def format_debug_state(title: str, snapshot: dict) -> str:
    lines = [f"{title} debug state, generated at {time.strftime('%Y-%m-%d %H:%M:%S')}:"]

    def emit(key: str, value, indent: int) -> None:
        pad = "  " * indent
        if isinstance(value, dict):
            lines.append(f"{pad}{key}:")
            for k in sorted(value, key=str):
                emit(str(k), value[k], indent + 1)
        elif isinstance(value, (list, tuple)):
            lines.append(f"{pad}{key}: ({len(value)} entries)")
            for i, item in enumerate(value):
                emit(f"[{i}]", item, indent + 1)
        else:
            lines.append(f"{pad}{key}: {value}")

    for key in sorted(snapshot, key=str):
        emit(str(key), snapshot[key], 1)
    return "\n".join(lines) + "\n"


def write_debug_state(path: str, title: str, snapshot: dict) -> None:
    """Render + write atomically (rename over the previous dump)."""
    text = format_debug_state(title, snapshot)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
