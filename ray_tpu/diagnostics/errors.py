"""ErrorEvent: the structured record carried on the GCS error-info channel.

Reference: ``src/ray/gcs/pubsub`` RAY_ERROR_INFO_CHANNEL +
``ray._private.utils.publish_error_to_driver`` — worker errors reach the
driver through the control plane, not through scraping logs. Events are
plain dicts on the wire (msgpack-friendly); ``ErrorEvent`` is a typed
view for in-process consumers.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

ERROR_INFO_CHANNEL = "error_info"

# Chaos convention: every fault the chaos subsystem injects publishes an
# ErrorEvent with ``source="chaos"`` and ``extra={"chaos": True, ...}``
# so list_errors()/doctor/traces can separate injected pain from organic
# failures (chaos/runner.py tags them; RecoveryVerifier relies on it).
CHAOS_SOURCE = "chaos"


def is_chaos_event(event: dict) -> bool:
    """True when the event was published by an injected fault."""
    return bool(event.get("source") == CHAOS_SOURCE
                or (event.get("extra") or {}).get("chaos"))


@dataclass
class ErrorEvent:
    type: str  # task_failure | actor_creation_failure | replica_start_failure | lease_wedge | oom_kill | ...
    source: str  # worker | raylet | gcs | serve_controller | serve_replica | ...
    message: str
    traceback: str = ""
    node_id: str = ""
    worker_id: str = ""
    actor_id: str = ""
    job_id: str = ""
    timestamp: float = 0.0
    extra: dict = field(default_factory=dict)

    def to_wire(self) -> dict:
        return {
            "type": self.type,
            "source": self.source,
            "message": self.message,
            "traceback": self.traceback,
            "node_id": self.node_id,
            "worker_id": self.worker_id,
            "actor_id": self.actor_id,
            "job_id": self.job_id,
            "timestamp": self.timestamp or time.time(),
            "extra": self.extra or {},
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "ErrorEvent":
        return cls(
            type=wire.get("type", ""),
            source=wire.get("source", ""),
            message=wire.get("message", ""),
            traceback=wire.get("traceback", ""),
            node_id=wire.get("node_id", ""),
            worker_id=wire.get("worker_id", ""),
            actor_id=wire.get("actor_id", ""),
            job_id=wire.get("job_id", ""),
            timestamp=wire.get("timestamp", 0.0),
            extra=wire.get("extra") or {},
        )


def make_event(
    error_type: str,
    message: str,
    *,
    source: str,
    traceback: str = "",
    node_id: str = "",
    worker_id: str = "",
    actor_id: str = "",
    job_id: str = "",
    extra: dict | None = None,
) -> dict:
    """Build a wire-format event dict."""
    return ErrorEvent(
        type=error_type,
        source=source,
        message=message,
        traceback=traceback,
        node_id=node_id,
        worker_id=worker_id,
        actor_id=actor_id,
        job_id=job_id,
        timestamp=time.time(),
        extra=extra or {},
    ).to_wire()


def publish_error_to_driver(
    error_type: str,
    message: str,
    *,
    source: str = "worker",
    traceback: str = "",
    actor_id: str = "",
    extra: dict | None = None,
) -> None:
    """Fire-and-forget an ErrorEvent from any connected process (worker,
    serve replica/controller, driver). Never raises: diagnostics must not
    turn a failure into a different failure."""
    try:
        from ..core.worker import global_worker

        w = global_worker()
        job = getattr(w, "job_id", None)
        event = make_event(
            error_type,
            message,
            source=source,
            traceback=traceback,
            node_id=getattr(w, "node_id", "") or "",
            worker_id=getattr(w, "worker_id", "") or "",
            actor_id=actor_id,
            job_id=str(job.int_value()) if job is not None else "",
            extra=extra,
        )
        w.io.run_coro(w.gcs.call("PublishError", {"event": event}, 10.0))
    except Exception:
        pass
