"""ray_tpu.diagnostics: cluster failure visibility.

Three legs (reference: ``ray._private.utils.publish_error_to_driver``,
the raylet's periodic ``debug_state.txt`` dumps, and the ``ray
health-check`` / ``ray status`` CLIs):

  * an **error-info pub/sub channel** through the GCS: any worker,
    raylet, or serve component publishes a structured ``ErrorEvent``;
    drivers auto-subscribe and log them, and ``util.state.list_errors()``
    queries the retained buffer;
  * **debug-state dumps**: every raylet (lease queue, worker pool,
    store/spill counters) and the GCS (actor/PG FSM counts) periodically
    snapshot their internals to ``debug_state_*.txt`` in the session dir,
    and serve the same snapshot over a ``GetDebugState`` RPC;
  * a **lease-wedge watchdog** in the raylet that fires an ErrorEvent
    (with a full queue snapshot) when a lease sits pending past a
    threshold while matching resources are free — the head-of-line /
    missed-wake signature of a wedged admission queue.
"""

from .errors import (
    ERROR_INFO_CHANNEL,
    ErrorEvent,
    make_event,
    publish_error_to_driver,
)
from .debug_state import format_debug_state, write_debug_state

__all__ = [
    "ERROR_INFO_CHANNEL",
    "ErrorEvent",
    "format_debug_state",
    "make_event",
    "publish_error_to_driver",
    "write_debug_state",
]
