"""Speculative-decoding bench: plain vs draft-K/verify decode tok/s.

ISSUE 13 acceptance cells, runnable standalone (``python -m ray_tpu.cli
bench speculative``) or inside ``bench.py``:

  * ``decode_tok_s_plain`` / ``decode_tok_s_speculative`` — steady-state
    engine decode throughput of the same repetitive-traffic batch
    through the plain fused-loop path and the draft-K/verify path. The
    on-chip acceptance bound (speculative ≥ 1.5× plain — decode there
    is weight-bandwidth-bound, so K+1 positions cost ~one forward) is
    owed with the next chip BENCH (ROADMAP 1b); this CPU sandbox is
    compute-bound per token, so only the cells + the ratio are recorded.
  * ``spec_accept_rate`` — drafted tokens the target accepted (0-1).
  * ``spec_tokens_per_dispatch`` — tokens emitted per slot per verify
    forward; the sandbox acceptance bar is strictly > 1.0 with the
    n-gram drafter on this repetitive traffic (accept-0 floors it at
    1.0, so speculation never pays more forwards per token than plain).
  * ``spec_parity`` — 1.0 iff the speculative greedy bytes match plain.

Set ``RAY_TPU_BENCH_SKIP_SPECULATIVE=1`` to leave ``*_skipped`` markers
that ``bench_check`` honors.
"""

from __future__ import annotations

import os
import time

SKIP_MARKERS = {
    "decode_tok_s_plain_skipped": True,
    "decode_tok_s_speculative_skipped": True,
    "spec_accept_rate_skipped": True,
    "spec_tokens_per_dispatch_skipped": True,
    "spec_parity_skipped": True,
}


def _prompts(n: int, length: int) -> list[list[int]]:
    """Repetitive prompts (distinct per slot): the traffic shape the
    n-gram self-drafter exists for — multi-turn resends, retrieval
    quotes, structured output."""
    out = []
    for i in range(n):
        period = [11 + i, 23, 37, 41 + i, 5, 17]
        out.append([period[j % len(period)] % 200 + 1
                    for j in range(length)])
    return out


def _bench_model(preset: str):
    """Config + params for the bench engines. Off-chip the dense path is
    the decode ground truth and must run f32: greedy parity between the
    chunk-shaped verify softmax and the pool-gather decode softmax is
    exact at f32, while bf16 can flip argmax near-ties on ulp-level
    reassociation. On chip the engines resolve to the paged kernel,
    whose verify/decode layouts are IDENTICAL — bf16 parity holds there
    by construction (tests/test_speculative.py covers both)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import PRESETS, init_params

    cfg = PRESETS[preset]
    if jax.default_backend() not in ("tpu", "axon"):
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  attn_impl="reference")
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _run_decode(cfg, params, speculation, prompts, max_new: int,
                max_len: int, page_size: int):
    """One timed generation of the batch; returns (tok_s, outputs,
    engine)."""
    from ray_tpu.llm.engine import InferenceEngine, Request

    eng = InferenceEngine(
        cfg, params, max_slots=len(prompts), max_len=max_len,
        page_size=page_size, prefill_chunk_size=4 * page_size,
        speculation_config=speculation, seed=0)
    reqs = [Request(f"sb-{i}", list(p), max_new)
            for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    t0 = time.perf_counter()
    while any(not r.done for r in reqs):
        eng.step()
    dt = time.perf_counter() - t0
    total = sum(len(r.generated) for r in reqs)
    return total / dt, [list(r.generated) for r in reqs], eng


def run_speculative_bench(slots: int | None = None,
                          max_new: int | None = None,
                          draft_k: int | None = None) -> dict:
    if os.environ.get("RAY_TPU_BENCH_SKIP_SPECULATIVE") == "1":
        return dict(SKIP_MARKERS)
    preset = os.environ.get("RAY_TPU_SPEC_BENCH_PRESET", "debug-128")
    slots = slots or int(os.environ.get("RAY_TPU_SPEC_BENCH_SLOTS", "8"))
    max_new = max_new or int(os.environ.get("RAY_TPU_SPEC_BENCH_NEW", "96"))
    draft_k = draft_k or int(os.environ.get("RAY_TPU_SPEC_BENCH_K", "6"))
    page_size = 16
    prompt_len = int(os.environ.get("RAY_TPU_SPEC_BENCH_PROMPT", "48"))
    max_len = -(-(prompt_len + max_new + page_size) // page_size) * page_size
    prompts = _prompts(slots, prompt_len)
    spec_cfg = {"num_draft_tokens": draft_k}
    cfg, params = _bench_model(preset)

    # Warmup pair: compiles the prefill buckets, the fused decode loop,
    # AND the verify program off-measurement (steady-state serving never
    # sees first-touch XLA compiles).
    _run_decode(cfg, params, None, prompts, 8, max_len, page_size)
    _run_decode(cfg, params, spec_cfg, prompts, 8, max_len, page_size)

    plain_tok_s, plain_out, _ = _run_decode(
        cfg, params, None, prompts, max_new, max_len, page_size)
    spec_tok_s, spec_out, eng = _run_decode(
        cfg, params, spec_cfg, prompts, max_new, max_len, page_size)
    return {
        "decode_tok_s_plain": round(plain_tok_s, 1),
        "decode_tok_s_speculative": round(spec_tok_s, 1),
        "spec_accept_rate": round(eng.spec_accept_rate, 4),
        "spec_tokens_per_dispatch": round(eng.spec_tokens_per_dispatch, 3),
        "spec_parity": 1.0 if spec_out == plain_out else 0.0,
        "spec_drafted_tokens": eng.metrics["spec_drafted_tokens"],
        "spec_dispatches": eng.metrics["spec_dispatches"],
        "spec_draft_k_cfg": draft_k,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_speculative_bench()))
