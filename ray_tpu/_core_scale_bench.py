"""Cluster-scale core bench: many raylets, one GCS, one host (ROADMAP 4).

The single-node suite (``_core_bench.py``) measures the owner→raylet hot
path; this one stands up a MANY-RAYLET harness (``cluster_utils.Cluster``
— raylets are real asyncio services, workers are real subprocesses) and
drives the reference's cluster-scale shape: a task storm spilling across
nodes and a 1k-actor creation storm landing on runtime-env-keyed zygote
pools, all flushing task events into the sharded GCS store concurrently.

Metrics (guarded by ``ray_tpu.bench_check``):

  * ``core_scale_tasks_per_s``            — no-op round trips across N raylets
  * ``core_scale_actor_creations_per_s``  — creation-storm throughput
  * ``core_scale_pooled_spawn_frac``      — fraction of spawns served by
                                            zygote-pool forks during the run
  * ``core_scale_{raylets,tasks,actors}_cfg`` — size echoes (inputs)
  * ``core_scale_chaos_verify_ok``        — 1.0 when the ``actor-storm``
                                            FaultPlan run ends
                                            RecoveryVerifier-green
                                            (``chaos=True`` runs only)

Defaults are the 10x-PR-6 acceptance sizes (8 raylets / 100k tasks /
1k actors); every size is env-tunable (``RAY_TPU_CORE_SCALE_*``) so a
1-core sandbox can run a shrunk variant of the same code path, and
``RAY_TPU_BENCH_SKIP_CORE_SCALE=1`` emits the ``core_scale_skipped``
marker ``bench_check`` honors instead of silently vanishing the cells.
"""

from __future__ import annotations

import os
import time


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def run_core_scale_bench(*, raylets: int | None = None,
                         num_tasks: int | None = None,
                         num_actors: int | None = None,
                         chaos: bool = False,
                         chaos_seed: int = 0) -> dict:
    """Run the many-raylet scale phases and return the metrics dict.

    Must be called with no cluster initialized in this process: the
    harness owns init/shutdown (the driver attaches to the harness GCS
    with a 0-CPU local raylet, so every lease spills to the scale
    raylets)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    raylets = raylets or _env_int("RAY_TPU_CORE_SCALE_RAYLETS", 8)
    num_tasks = num_tasks or _env_int("RAY_TPU_CORE_SCALE_TASKS", 100_000)
    num_actors = num_actors or _env_int("RAY_TPU_CORE_SCALE_ACTORS", 1000)

    out: dict = {
        "core_scale_raylets_cfg": raylets,
        "core_scale_tasks_cfg": num_tasks,
        "core_scale_actors_cfg": num_actors,
    }

    # Per-raylet CPU pool: the actor storm pins one CPU token per live
    # actor, plus headroom for the task pipelines.
    cpus_per_node = max(8, (num_actors + raylets - 1) // raylets + 8)
    # Zygote pool sized per raylet for its share of the storm (echoed as
    # a _cfg input, restored on exit).
    pool = _env_int("RAY_TPU_CORE_SCALE_POOL",
                    min(32, max(4, num_actors // raylets)))
    out["core_scale_pool_cfg"] = pool
    from ray_tpu.core.config import get_config

    cfg = get_config()
    saved = {k: getattr(cfg, k)
             for k in ("zygote_pool_size", "zygote_pool_refill_batch")}
    cfg.zygote_pool_size = pool
    cfg.zygote_pool_refill_batch = 8
    cluster = Cluster(initialize_head=False)
    for _ in range(raylets):
        cluster.add_node(wait=False, num_cpus=cpus_per_node)
    cluster.wait_for_nodes(raylets)
    ray_tpu.init(address=cluster.address, num_cpus=0)

    @ray_tpu.remote
    def _noop():
        return None

    @ray_tpu.remote(max_restarts=2)
    class _Counter:
        def __init__(self):
            self.n = 0

        def ping(self, i):
            self.n += 1
            return i

    try:
        # Warmup: every raylet boots its zygote + prestart pool and the
        # driver's spillback path compiles before the timed windows.
        ray_tpu.get([_noop.remote() for _ in range(raylets * 8)],
                    timeout=300)

        # --- phase 1: cross-raylet task storm ---------------------------
        t0 = time.perf_counter()
        refs = [_noop.remote() for _ in range(num_tasks)]
        ray_tpu.get(refs, timeout=3600)
        dt = time.perf_counter() - t0
        del refs
        out["core_scale_tasks_per_s"] = round(num_tasks / dt, 1)

        # --- phase 2: actor creation storm ------------------------------
        spawn_before = _spawn_totals(cluster)
        t0 = time.perf_counter()
        actors = [_Counter.remote() for _ in range(num_actors)]
        ray_tpu.get([a.ping.remote(0) for a in actors], timeout=3600)
        create_dt = time.perf_counter() - t0
        out["core_scale_actor_creations_per_s"] = round(
            num_actors / create_dt, 1)
        spawn_after = _spawn_totals(cluster)
        delta = {k: spawn_after.get(k, 0) - spawn_before.get(k, 0)
                 for k in ("cold", "pooled")}
        spawned = sum(delta.values())
        if spawned:
            out["core_scale_pooled_spawn_frac"] = round(
                delta["pooled"] / spawned, 4)
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        del actors
        time.sleep(1.0)

        # --- phase 3 (optional): actor-storm chaos plan ------------------
        if chaos:
            out.update(_chaos_phase(num_actors, _Counter, seed=chaos_seed))
    finally:
        for k, v in saved.items():
            setattr(cfg, k, v)
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()
    return out


def _spawn_totals(cluster) -> dict:
    totals = {"cold": 0, "pooled": 0}
    for raylet in cluster.nodes:
        for mode, n in raylet._spawn_stats.items():
            totals[mode] = totals.get(mode, 0) + n
    return totals


def _chaos_phase(num_actors: int, actor_cls, seed: int = 0) -> dict:
    """Run the bundled ``actor-storm`` FaultPlan against a reduced storm
    (a tenth of the main storm, at least 20 actors) and verify recovery."""
    import ray_tpu
    from ray_tpu import chaos

    storm = max(20, num_actors // 10)

    def workload() -> dict:
        actors = [actor_cls.remote() for _ in range(storm)]
        ok = failures = 0
        for a in actors:
            try:
                ray_tpu.get(a.ping.remote(0), timeout=300)
                ok += 1
            except Exception:
                failures += 1
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        del actors
        return {"actors": storm, "ok": ok, "failures": failures}

    try:
        report = chaos.run_plan("actor-storm", seed=seed, workload=workload,
                                verify_timeout_s=180)
        return {
            "core_scale_chaos_verify_ok": 1.0 if report["verify"]["ok"] else 0.0,
            "core_scale_chaos_storm_cfg": storm,
        }
    except chaos.ChaosVerificationError:
        return {"core_scale_chaos_verify_ok": 0.0,
                "core_scale_chaos_storm_cfg": storm}


def main() -> int:
    import json
    import sys

    result = run_core_scale_bench()
    print(json.dumps(result))
    return 0 if result.get("core_scale_tasks_per_s") else 1


if __name__ == "__main__":
    raise SystemExit(main())
