"""Multi-node test harness: many raylets, one GCS, one host.

Equivalent of the reference's ``python/ray/cluster_utils.py:135``
(``Cluster.add_node`` / ``remove_node``) — the backbone of its distributed
test strategy (SURVEY.md §4.1). Raylets run as asyncio services on one
dedicated thread; their worker processes are real subprocesses, so task
execution, object transfer and failure detection cross real process
boundaries exactly as in production. Node death is simulated by killing a
raylet's server + workers without a drain; the GCS discovers it through
failed health checks, as it would a crashed host.
"""

from __future__ import annotations

import os
import time

from .core.config import get_config
from .core.gcs import GcsServer
from .core.raylet import Raylet
from .core.rpc import EventLoopThread


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: dict | None = None,
        _system_config: dict | None = None,
        enable_gcs_ft: bool = False,
    ):
        if _system_config:
            get_config().apply_dict(_system_config)
        self._loop = EventLoopThread("raytpu-cluster")
        self._gcs_storage = None
        self._gcs_ft_dir: str | None = None
        if enable_gcs_ft:
            import tempfile

            from .core.gcs_storage import FileStorage

            self._gcs_ft_dir = tempfile.mkdtemp(prefix="raytpu-gcs-ft-")
            self._gcs_storage = FileStorage(
                os.path.join(self._gcs_ft_dir, "gcs_tables.msgpack")
            )
        self.gcs = GcsServer(storage=self._gcs_storage)
        self._loop.run_sync(self.gcs.start())
        self.nodes: list[Raylet] = []
        self.head_node: Raylet | None = None
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        """GCS address — pass to ``ray_tpu.init(address=...)``."""
        return self.gcs.address

    def add_node(self, wait: bool = True, **node_args) -> Raylet:
        """Start one more raylet joined to this cluster's GCS."""
        raylet = Raylet(self.gcs.address, **node_args)
        self._loop.run_sync(raylet.start())
        self.nodes.append(raylet)
        if wait:
            self.wait_for_nodes(len(self.nodes))
        return raylet

    def remove_node(self, raylet: Raylet, allow_graceful: bool = False) -> None:
        """Take a node down. Non-graceful (default) simulates a crashed
        host: workers SIGKILLed, no drain — the GCS must detect the death
        via health checks and run its node-failure handling."""
        if raylet in self.nodes:
            self.nodes.remove(raylet)
        if allow_graceful:
            self._loop.run_sync(raylet.stop(), timeout=15)
            self._loop.run_sync(
                self.gcs.handle_DrainNode({"node_id": raylet.node_id.hex()})
            )
        else:
            self._loop.run_sync(raylet.kill(), timeout=15)

    def crash_gcs(self) -> None:
        """Kill the GCS abruptly (no final snapshot flush) — reference
        equivalent: SIGKILL the gcs_server process in FT tests."""
        self._loop.run_sync(self.gcs.crash(), timeout=10)

    def restart_gcs(self) -> None:
        """Start a fresh GCS on the SAME port with the same storage; it
        restores durable tables and raylets re-register on heartbeat.
        Requires enable_gcs_ft=True for state to survive."""
        port = self.gcs.port
        self.gcs = GcsServer(port=port, storage=self._gcs_storage)
        self._loop.run_sync(self.gcs.start())

    def wait_for_nodes(self, count: int, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [n for n in self.gcs._nodes.values() if n["state"] == "ALIVE"]
            if len(alive) >= count:
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {count} alive nodes in {timeout}s")

    def wait_for_node_death(self, raylet: Raylet, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        node_id = raylet.node_id.hex()
        while time.monotonic() < deadline:
            node = self.gcs._nodes.get(node_id)
            if node is not None and node["state"] == "DEAD":
                return
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id[:8]} not marked DEAD in {timeout}s")

    def shutdown(self) -> None:
        for raylet in list(self.nodes):
            try:
                self._loop.run_sync(raylet.stop(), timeout=15)
            except Exception:
                pass
        self.nodes = []
        try:
            self._loop.run_sync(self.gcs.stop(), timeout=5)
        except Exception:
            pass
        self._loop.stop()
        if self._gcs_ft_dir is not None:
            import shutil

            shutil.rmtree(self._gcs_ft_dir, ignore_errors=True)
            self._gcs_ft_dir = None
