"""ray_tpu: a TPU-native distributed computing framework.

A ground-up re-design of Ray's capabilities (tasks, actors, a distributed
shared-memory object store with ownership and lineage, per-node scheduling
with cluster spillback, placement groups, and the ML libraries: Data,
Train, Tune, Serve, an LLM engine and an RL learner stack) for TPU
hardware: JAX/XLA/Pallas for all device compute, `jax.sharding` meshes +
collectives over ICI/DCN instead of NCCL, and a native C++ shared-memory
object store. See SURVEY.md at the repo root for the reference analysis
this build follows.
"""

from .core.api import (
    ActorClass,
    ActorHandle,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from .core.object_ref import ObjectRef
from .core.generator import ObjectRefGenerator
from .core import status as exceptions
from .core.status import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    RayTaskError,
    RayTpuError,
    TaskCancelledError,
    WorkerCrashedError,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy subsystems: `ray_tpu.chaos.run_plan(...)` works right after
    # `import ray_tpu` without paying the import on every startup.
    if name == "chaos":
        import importlib

        return importlib.import_module(".chaos", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
