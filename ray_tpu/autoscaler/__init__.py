"""Autoscaler: demand-driven reconciliation of TPU worker pools.

Equivalent of the reference's autoscaler v2
(``python/ray/autoscaler/v2/scheduler.py:624`` ResourceDemandScheduler +
``instance_manager``): pending lease shapes (reported by raylets in
heartbeats), unplaceable placement groups, and explicit
``request_resources`` floors are bin-packed against live capacity; the
shortfall launches typed nodes through a NodeProvider, and idle nodes
above ``min_workers`` are terminated after a timeout.
"""

from .autoscaler import Autoscaler, NodeTypeConfig
from .gce import GceTpuNodeProvider
from .gke import GkeTpuNodeProvider
from .instance_manager import Instance, InstanceManager
from .node_provider import LocalNodeProvider, NodeProvider
from .sdk import request_resources

__all__ = [
    "Autoscaler",
    "GceTpuNodeProvider",
    "GkeTpuNodeProvider",
    "Instance",
    "InstanceManager",
    "NodeTypeConfig",
    "NodeProvider",
    "LocalNodeProvider",
    "request_resources",
]
