"""NodeProvider: the cloud-side interface the reconciler drives.

Equivalent of the reference's ``python/ray/autoscaler/node_provider.py``
(create/terminate/list). A real TPU deployment implements this against
its pod/VM API (e.g. GKE or queued resources); ``LocalNodeProvider``
backs it with in-process raylets on the Cluster harness so autoscaling
is testable end-to-end — launched "nodes" really join the GCS and run
work.
"""

from __future__ import annotations

import threading


class NodeLaunchError(Exception):
    """A node launch the provider could not fulfil. ``transient=True``
    marks capacity-class failures (quota exhausted, zone stockout — the
    dominant real TPU failure) the reconciler should back off on and
    route around, rather than config errors worth surfacing loudly."""

    def __init__(self, message: str, *, transient: bool = False,
                 reason: str = ""):
        super().__init__(message)
        self.transient = transient
        self.reason = reason


class NodeProvider:
    def create_node(self, node_type: str, resources: dict) -> str:
        """Launch a node of `node_type`; returns a provider instance id."""
        raise NotImplementedError

    def terminate_node(self, instance_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> dict[str, str]:
        """instance_id -> node_type for nodes this provider launched."""
        raise NotImplementedError

    def node_id_of(self, instance_id: str) -> str | None:
        """Cluster node id (hex) for a launched instance, once known."""
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch raylets on a ``cluster_utils.Cluster`` (the harness plays the
    role of the cloud API)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._lock = threading.Lock()
        self._instances: dict[str, dict] = {}  # instance_id -> {type, raylet}
        self._counter = 0
        self._preempted: dict[str, str] = {}  # instance_id -> node_type

    def create_node(self, node_type: str, resources: dict) -> str:
        res = dict(resources)
        num_cpus = res.pop("CPU", 0)
        raylet = self.cluster.add_node(wait=False, num_cpus=num_cpus, resources=res)
        with self._lock:
            self._counter += 1
            iid = f"local-{node_type}-{self._counter}"
            self._instances[iid] = {"type": node_type, "raylet": raylet}
        return iid

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            inst = self._instances.pop(instance_id, None)
        if inst is not None:
            self.cluster.remove_node(inst["raylet"], allow_graceful=True)

    def non_terminated_nodes(self) -> dict[str, str]:
        with self._lock:
            return {iid: inst["type"] for iid, inst in self._instances.items()}

    def node_id_of(self, instance_id: str) -> str | None:
        with self._lock:
            inst = self._instances.get(instance_id)
        return inst["raylet"].node_id.hex() if inst else None

    # ------------------------------------------------------------- preemption
    def preempt_node(self, instance_id: str,
                     grace_s: float | None = None) -> bool:
        """Simulate a GCE spot reclaim of a launched node: the raylet gets
        a preemption notice (drains, then its workers die after the
        grace) and the instance surfaces in ``preemption_notices()`` so
        the reconciler terminates + replaces it — the full preemption
        path, end to end, on the in-process harness."""
        with self._lock:
            inst = self._instances.get(instance_id)
        if inst is None:
            return False
        self.cluster._loop.run_sync(inst["raylet"].handle_PreemptionNotice({
            "reason": "spot reclaim (simulated)", "grace_s": grace_s}))
        with self._lock:
            self._preempted[instance_id] = inst["type"]
        return True

    def preemption_notices(self) -> dict[str, str]:
        with self._lock:
            return dict(self._preempted)

    def ack_preemption(self, instance_id: str) -> None:
        with self._lock:
            self._preempted.pop(instance_id, None)
