"""The reconciler: demand + capacity -> launch/terminate decisions.

Equivalent of the reference's
``autoscaler/v2/scheduler.py:624`` (ResourceDemandScheduler.schedule):
each round it
  1. reads live nodes (+ per-node pending lease shapes) from the GCS,
  2. gathers demand: pending shapes, PENDING/INFEASIBLE placement-group
     bundles, and the ``request_resources`` floor,
  3. first-fit bin-packs demand onto current AVAILABLE capacity,
  4. launches the cheapest node type that fits each unmet shape (bounded
     by ``max_workers``),
  5. terminates nodes idle past ``idle_timeout_s`` (bounded by
     ``min_workers``).
Deliberately synchronous and stateless between rounds (modulo launch
cooldown): every decision is derivable from cluster state, as in v2's
instance-manager reconciler.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from .node_provider import NodeLaunchError, NodeProvider
from .sdk import REQUEST_KEY, get_requested_resources

logger = logging.getLogger(__name__)


@dataclass
class NodeTypeConfig:
    name: str
    resources: dict
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class _Decision:
    launch: list[str] = field(default_factory=list)      # node type names
    terminate: list[str] = field(default_factory=list)   # instance ids


def _fits(shape: dict, available: dict) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _consume(shape: dict, available: dict) -> None:
    for k, v in shape.items():
        available[k] = available.get(k, 0.0) - v


class Autoscaler:
    def __init__(
        self,
        gcs_call,
        provider: NodeProvider,
        node_types: list[NodeTypeConfig],
        *,
        idle_timeout_s: float = 5.0,
        launch_cooldown_s: float = 1.0,
        launch_backoff_base_s: float = 5.0,
        launch_backoff_max_s: float = 300.0,
    ):
        """``gcs_call(method, payload) -> dict`` — a synchronous GCS RPC
        (the driver worker's `_gcs_call` or a Cluster-loop closure)."""
        self._gcs_call = gcs_call
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.idle_timeout_s = idle_timeout_s
        self.launch_cooldown_s = launch_cooldown_s
        self.launch_backoff_base_s = launch_backoff_base_s
        self.launch_backoff_max_s = launch_backoff_max_s
        # Per-node-type launch backoff after quota/stockout failures:
        # type -> (retry_after_ts, consecutive_failures). Types in
        # backoff are skipped during selection, so demand routes to the
        # next fitting type instead of hammering an exhausted one
        # (VERDICT r3 weak #7; ref: v2 instance-manager allocation retry).
        self._launch_backoff: dict[str, tuple[float, int]] = {}
        self._idle_since: dict[str, float] = {}  # instance_id -> ts
        self._last_launch = 0.0
        # Launched instances not yet registered with the GCS: their
        # capacity counts during bin-packing so slow node boots don't
        # trigger a re-launch storm (reference: instance-manager pending
        # instances). Entries expire after `boot_timeout_s`.
        self._pending_launches: dict[str, tuple[str, float]] = {}  # iid -> (type, ts)
        self.boot_timeout_s = 120.0
        self._warned_unfittable: set = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- lifecycle
    def start(self, period_s: float = 0.5) -> None:
        def loop():
            while not self._stop.is_set():
                try:
                    self.reconcile_once()
                except Exception:
                    logger.exception("autoscaler reconcile failed")
                self._stop.wait(period_s)

        self._thread = threading.Thread(target=loop, daemon=True, name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # ------------------------------------------------------------- one round
    def _collect_demand(self, nodes: list[dict]) -> list[dict]:
        demand: list[dict] = []
        for node in nodes:
            if node.get("state") != "ALIVE":
                continue
            for entry in node.get("pending_demand") or []:
                demand.extend([dict(entry["shape"])] * int(entry["count"]))
        # Unplaced placement groups: every bundle is a demand shape.
        pgs = self._gcs_call("ListPlacementGroups", {}).get("placement_groups", [])
        for pg in pgs:
            if pg.get("state") in ("PENDING", "INFEASIBLE"):
                demand.extend([dict(b) for b in pg.get("bundles", [])])
        return demand

    def _capacity_views(self, nodes: list[dict]):
        available, total = [], []
        for node in nodes:
            # Draining (preempted) nodes must not absorb demand during
            # bin-packing — the replacement launch they displaced is the
            # entire point of surfacing the notice early.
            if node.get("state") != "ALIVE" or node.get("draining"):
                continue
            res = node.get("resources") or {}
            available.append(dict(res.get("available") or {}))
            total.append(dict(res.get("total") or {}))
        return available, total

    def _type_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for t in self.provider.non_terminated_nodes().values():
            counts[t] = counts.get(t, 0) + 1
        return counts

    def _fits_some_type(self, shape: dict) -> bool:
        return any(_fits(shape, dict(t.resources)) for t in self.node_types.values())

    def _expire_pending_launches(self, nodes: list[dict]) -> None:
        registered = {n["node_id"] for n in nodes if n.get("state") == "ALIVE"}
        now = time.time()
        for iid, (_type, ts) in list(self._pending_launches.items()):
            if self.provider.node_id_of(iid) in registered or now - ts > self.boot_timeout_s:
                self._pending_launches.pop(iid, None)

    def _in_backoff(self, type_name: str) -> bool:
        entry = self._launch_backoff.get(type_name)
        return entry is not None and time.time() < entry[0]

    def _try_launch(self, type_name: str) -> str | None:
        """create_node with capacity-failure handling: a transient
        failure (quota/stockout) puts the TYPE in exponential backoff and
        returns None — the round continues with other types/decisions
        instead of aborting."""
        try:
            iid = self.provider.create_node(
                type_name, self.node_types[type_name].resources)
        except NodeLaunchError as e:
            if not e.transient:
                raise
            _until, failures = self._launch_backoff.get(type_name, (0.0, 0))
            delay = min(self.launch_backoff_max_s,
                        self.launch_backoff_base_s * (2 ** failures))
            self._launch_backoff[type_name] = (time.time() + delay, failures + 1)
            logger.warning(
                "launch of %s failed (%s); backing off %.0fs (attempt %d)",
                type_name, e.reason or e, delay, failures + 1)
            return None
        self._launch_backoff.pop(type_name, None)
        self._pending_launches[iid] = (type_name, time.time())
        return iid

    def reconcile_once(self) -> _Decision:
        nodes = self._gcs_call("GetAllNodes", {}).get("nodes", [])
        decision = _Decision()

        self._expire_pending_launches(nodes)
        demand = self._collect_demand(nodes)
        available, total = self._capacity_views(nodes)
        # Booting nodes count as capacity (they haven't registered yet),
        # else every reconcile round until registration re-launches for
        # the same demand.
        for _iid, (type_name, _ts) in self._pending_launches.items():
            cfg = self.node_types.get(type_name)
            if cfg is not None:
                available.append(dict(cfg.resources))
                total.append(dict(cfg.resources))

        # Explicit floor: bundles that must fit in TOTAL capacity.
        floor = get_requested_resources(
            lambda key: self._gcs_call("KvGet", {"key": key}).get("value")
        )
        floor_unmet = []
        total_copy = [dict(t) for t in total]
        for bundle in floor:
            for cap in total_copy:
                if _fits(bundle, cap):
                    _consume(bundle, cap)
                    break
            else:
                floor_unmet.append(bundle)

        # Load demand: bundles that must fit in AVAILABLE capacity.
        unmet = list(floor_unmet)
        for shape in demand:
            for cap in available:
                if _fits(shape, cap):
                    _consume(shape, cap)
                    break
            else:
                unmet.append(shape)

        # Shapes no node type can EVER satisfy are hopeless, not pending:
        # drop them from `unmet` (warn once per shape) so they can't
        # immortalize idle nodes via the scale-down guard below.
        satisfiable = []
        for shape in unmet:
            if self._fits_some_type(shape):
                satisfiable.append(shape)
            else:
                key = tuple(sorted(shape.items()))
                if key not in self._warned_unfittable:
                    self._warned_unfittable.add(key)
                    logger.warning("autoscaler: no node type fits shape %s — ignoring", shape)
        unmet = satisfiable

        # Launch for unmet shapes (respecting per-type max and cooldown).
        if unmet and time.time() - self._last_launch >= self.launch_cooldown_s:
            counts = self._type_counts()
            pending_capacity: list[dict] = []
            for shape in unmet:
                placed = False
                for cap in pending_capacity:  # a node just decided on may absorb more
                    if _fits(shape, cap):
                        _consume(shape, cap)
                        placed = True
                        break
                if placed:
                    continue
                for t in self.node_types.values():
                    if counts.get(t.name, 0) + decision.launch.count(t.name) >= t.max_workers:
                        continue
                    if self._in_backoff(t.name):
                        continue  # quota/stockout: route to the next type
                    if _fits(shape, dict(t.resources)):
                        decision.launch.append(t.name)
                        cap = dict(t.resources)
                        _consume(shape, cap)
                        pending_capacity.append(cap)
                        placed = True
                        break
                if not placed:
                    pass  # at max_workers for every fitting type: wait
            # re-check backoff per launch: the FIRST quota failure this
            # round must stop further create calls for the same type
            launched = [n for n in decision.launch
                        if not self._in_backoff(n) and self._try_launch(n)]
            decision.launch = launched
            if launched:
                self._last_launch = time.time()
                logger.info("autoscaler launched: %s", launched)

        # min_workers floor: keep at least min_workers of each type.
        # (provider counts already include this round's launches)
        counts = self._type_counts()
        for t in self.node_types.values():
            if self._in_backoff(t.name):
                continue
            for _ in range(t.min_workers - counts.get(t.name, 0)):
                if self._try_launch(t.name) is None:
                    break
                decision.launch.append(t.name)

        # Idle termination with per-node busy tracking: a node's timer only
        # resets when THAT node is busy — unrelated trickle load elsewhere
        # must not immortalize an idle node. Nodes holding the
        # request_resources floor are exempt.
        node_by_id = {n["node_id"]: n for n in nodes if n.get("state") == "ALIVE"}
        counts = self._type_counts()
        floor_held = self._floor_held_instances(floor, node_by_id)
        now = time.time()
        for iid, type_name in list(self.provider.non_terminated_nodes().items()):
            node = node_by_id.get(self.provider.node_id_of(iid))
            if node is None:
                continue
            res = node.get("resources") or {}
            avail, tot = res.get("available") or {}, res.get("total") or {}
            busy = any(avail.get(k, 0.0) < v for k, v in tot.items()) or (
                node.get("pending_demand") or []
            )
            if busy:
                self._idle_since.pop(iid, None)
                continue
            first_idle = self._idle_since.setdefault(iid, now)
            if unmet:
                continue  # capacity crunch: don't shrink (timers keep running)
            cfg = self.node_types.get(type_name)
            if (
                cfg is not None
                and iid not in floor_held
                and counts.get(type_name, 0) > cfg.min_workers
                and now - first_idle >= self.idle_timeout_s
            ):
                logger.info("autoscaler terminating idle node %s (%s)", iid, type_name)
                self.provider.terminate_node(iid)
                self._idle_since.pop(iid, None)
                counts[type_name] -= 1
        return decision

    def _floor_held_instances(self, floor: list[dict], node_by_id: dict) -> set[str]:
        """Greedy-pack the request_resources floor onto provider nodes:
        every node that absorbs a floor bundle is exempt from idle
        termination (else the floor churns launch/terminate forever)."""
        held: set[str] = set()
        if not floor:
            return held
        remaining = [dict(b) for b in floor]
        for iid in self.provider.non_terminated_nodes():
            node = node_by_id.get(self.provider.node_id_of(iid))
            if node is None:
                continue
            cap = dict((node.get("resources") or {}).get("total") or {})
            for bundle in list(remaining):
                if _fits(bundle, cap):
                    _consume(bundle, cap)
                    remaining.remove(bundle)
                    held.add(iid)
        return held
