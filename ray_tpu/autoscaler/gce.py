"""GCE TPU-pod NodeProvider: autoscaling real TPU VM slices.

Equivalent of the reference's GCP provider
(``python/ray/autoscaler/_private/gcp/node_provider.py``) specialized
for TPU VMs (the reference's ``tpu.py`` accelerator path): nodes are TPU
VM slices created through the Cloud TPU REST API
(``tpu.googleapis.com/v2``), authenticated with the instance metadata
server's service-account token, and bootstrapped into the cluster via a
startup script that starts a raylet pointed at the head GCS.

Design notes:
  * Each "node" is an atomic SLICE (``accelerator_type`` like
    ``v5litepod-16``) — the TPU scheduling unit, matching the slice-head
    resource scheme the raylet advertises.
  * The HTTP transport is injectable: production uses urllib against the
    live APIs; tests drive the full provider + reconciler against a fake
    transport (this environment has zero egress, so live calls are also
    cleanly gated with an actionable error).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable

from .node_provider import NodeLaunchError, NodeProvider

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)

# Capacity-class failure markers in Cloud TPU error bodies: quota
# exhaustion and zone stockout (the dominant real-world TPU launch
# failures; the reconciler backs off this node type and tries others).
# Deliberately SPECIFIC — a 403 "API not enabled ... check quota project"
# config error must NOT match, or a permanent misconfiguration would be
# retried silently forever.
_CAPACITY_MARKERS = ("RESOURCE_EXHAUSTED", "QUOTA_EXCEEDED",
                     "Quota exceeded", "quota exceeded",
                     "stockout", "out of capacity", "no more capacity",
                     "insufficient capacity", "There is no more capacity")


def _classify_launch_error(e: Exception) -> Exception:
    """Wrap a create-node failure: HTTP 429 always, or an error whose
    body carries a capacity marker, becomes a TRANSIENT NodeLaunchError;
    anything else (auth, API-disabled, bad request) passes through."""
    if isinstance(e, NodeLaunchError):
        return e
    code = getattr(e, "code", None)
    body = ""
    try:
        body = e.read().decode(errors="replace") if hasattr(e, "read") else str(e)
    except Exception:
        body = str(e)
    if code == 429 or any(m in body for m in _CAPACITY_MARKERS):
        return NodeLaunchError(
            f"TPU capacity unavailable (HTTP {code}): {body[:300]}",
            transient=True, reason="quota/stockout")
    return e


class GceTransport:
    """Live transport: metadata-server auth + TPU REST calls."""

    def __init__(self, timeout_s: float = 30.0):
        self._timeout = timeout_s
        self._token: str | None = None
        self._token_expiry = 0.0

    def _auth_token(self) -> str:
        if self._token and time.time() < self._token_expiry - 60:
            return self._token
        req = urllib.request.Request(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                blob = json.loads(resp.read())
        except (urllib.error.URLError, OSError) as e:
            raise RuntimeError(
                "GceTpuNodeProvider needs the GCE metadata server (run on a "
                "GCE VM with a service account, or inject a transport): "
                f"{e}") from e
        self._token = blob["access_token"]
        self._token_expiry = time.time() + blob.get("expires_in", 3600)
        return self._token

    def request(self, method: str, url: str, body: dict | None = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method, headers={
            "Authorization": f"Bearer {self._auth_token()}",
            "Content-Type": "application/json",
        })
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}


class GceTpuNodeProvider(NodeProvider):
    """TPU VM slices as autoscaler nodes (reference gcp/node_provider.py
    + _private/accelerators/tpu.py provisioning path)."""

    API = "https://tpu.googleapis.com/v2"
    # How long a just-created node may be absent from the (eventually
    # consistent) list API before we conclude it never materialized.
    CREATE_GRACE_S = 300.0

    def __init__(
        self,
        project: str,
        zone: str,
        *,
        gcs_address: str,
        runtime_version: str = "tpu-ubuntu2204-base",
        node_types: dict[str, dict] | None = None,
        cluster_name: str = "raytpu",
        transport: Any = None,
        startup_script: str | None = None,
    ):
        """``node_types``: name -> {"accelerator_type": "v5litepod-16",
        "resources": {...}} (the shapes the reconciler may request)."""
        self.project = project
        self.zone = zone
        self.gcs_address = gcs_address
        self.runtime_version = runtime_version
        self.node_types = node_types or {}
        self.cluster_name = cluster_name
        self.transport = transport or GceTransport()
        self._startup = startup_script
        self._lock = threading.Lock()
        self._instances: dict[str, dict] = {}  # instance_id -> {type, state}
        self._counter = 0
        # Spot-reclaim notices: instances the cloud listed as PREEMPTED,
        # held until the reconciler acks them (preemption_notices /
        # ack_preemption) so it can terminate + replace the slice.
        self._preempted: dict[str, str] = {}  # instance_id -> node_type

    # ------------------------------------------------------------- helpers
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _node_url(self, instance_id: str) -> str:
        return f"{self.API}/{self._parent()}/nodes/{instance_id}"

    def _startup_script(self) -> str:
        if self._startup is not None:
            return self._startup
        # Every host of the slice starts a raylet joined to the head GCS;
        # the TPU accelerator manager advertises chips + the slice-head
        # resource so slice-atomic scheduling works (tpu.py).
        return (
            "#! /bin/bash\n"
            f"python -m ray_tpu.cli start --address={self.gcs_address} "
            "--num-cpus=$(nproc)\n"
        )

    # ------------------------------------------------------ NodeProvider API
    def create_node(self, node_type: str, resources: dict) -> str:
        spec = self.node_types.get(node_type)
        if spec is None:
            raise ValueError(f"unknown node_type {node_type!r} "
                             f"(configured: {list(self.node_types)})")
        with self._lock:
            self._counter += 1
            instance_id = f"{self.cluster_name}-{node_type}-{self._counter}"
            self._instances[instance_id] = {
                "type": node_type, "state": "CREATING", "created_at": time.time()}
        body = {
            "acceleratorType": spec["accelerator_type"],
            "runtimeVersion": spec.get("runtime_version", self.runtime_version),
            "networkConfig": {"enableExternalIps": False},
            "metadata": {"startup-script": self._startup_script()},
            "labels": {"raytpu-cluster": self.cluster_name,
                       "raytpu-node-type": node_type},
        }
        try:
            self.transport.request(
                "POST",
                f"{self.API}/{self._parent()}/nodes?nodeId={instance_id}",
                body,
            )
        except Exception as e:
            with self._lock:
                self._instances.pop(instance_id, None)
            raise _classify_launch_error(e) from e
        return instance_id

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            inst = self._instances.pop(instance_id, None)
        if inst is None:
            return
        try:
            self.transport.request("DELETE", self._node_url(instance_id))
        except Exception:
            with self._lock:  # keep tracking: the VM still exists
                self._instances[instance_id] = inst
            raise

    def non_terminated_nodes(self) -> dict[str, str]:
        # Reconcile against the API (nodes can die outside our control).
        try:
            listing = self.transport.request(
                "GET", f"{self.API}/{self._parent()}/nodes")
        except Exception:
            with self._lock:  # API hiccup: serve the cached view
                return {i: v["type"] for i, v in self._instances.items()}
        live: dict[str, str] = {}
        listed: set[str] = set()
        with self._lock:
            for node in listing.get("nodes", []):
                labels = node.get("labels") or {}
                if labels.get("raytpu-cluster") != self.cluster_name:
                    continue
                iid = node["name"].rsplit("/", 1)[-1]
                listed.add(iid)
                if node.get("state") in ("DELETING", "TERMINATED", "PREEMPTED"):
                    if node.get("state") == "PREEMPTED" and iid in self._instances:
                        # Surface the GCE spot reclaim as a typed notice
                        # the reconciler consumes (terminate + replace).
                        self._preempted[iid] = labels.get(
                            "raytpu-node-type", "unknown")
                    continue
                live[iid] = labels.get("raytpu-node-type", "unknown")
                entry = self._instances.setdefault(
                    iid, {"type": live[iid], "state": node.get("state", "")})
                # Track the observed state, but keep created_at until grace
                # expiry: a still-CREATING node can flap back OUT of an
                # eventually-consistent listing, and pruning it then would
                # re-enable the double-create.
                entry["state"] = node.get("state", entry.get("state", ""))
            for iid in list(self._instances):
                if iid in live:
                    continue
                # The TPU list API is eventually consistent: a node we just
                # created (CREATING, not yet visible in the listing) must
                # not be pruned, or the reconciler under-counts pending
                # nodes and double-creates the slice. Keep it — and report
                # it live — until it shows up in a listing (any state) or
                # exceeds a creation grace period. A node LISTED in a
                # terminal state is genuinely gone and is pruned.
                inst = self._instances[iid]
                created_at = inst.get("created_at")
                if (iid not in listed
                        and inst.get("state") == "CREATING"
                        and created_at is not None
                        and time.time() - created_at < self.CREATE_GRACE_S):
                    live[iid] = inst["type"]
                    continue
                self._instances.pop(iid)
        return live

    def node_id_of(self, instance_id: str) -> str | None:
        # The raylet started by the startup script registers itself with
        # the GCS; mapping instance -> cluster node id happens there (the
        # reconciler matches by pending-launch expiry, not identity).
        return None

    # ------------------------------------------------------------- preemption
    def preemption_notices(self) -> dict[str, str]:
        """instance_id -> node_type for slices the cloud reported
        PREEMPTED and nobody acked yet. The ``InstanceManager`` consumes
        these: terminate the instance, request a same-shape replacement."""
        with self._lock:
            return dict(self._preempted)

    def ack_preemption(self, instance_id: str) -> None:
        with self._lock:
            self._preempted.pop(instance_id, None)
