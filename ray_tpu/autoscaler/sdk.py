"""Autoscaler SDK: explicit capacity floors.

Reference: ``python/ray/autoscaler/sdk.py`` ``request_resources`` — ask
the autoscaler to hold capacity for the given bundles regardless of
current load (e.g. pre-scale before a burst). The request is stored in
the GCS KV and read by the reconciler each round; an empty list clears
it.
"""

from __future__ import annotations

import json

REQUEST_KEY = "__autoscaler_resource_requests"


def request_resources(bundles: list[dict] | None = None) -> None:
    from ..core.worker import global_worker

    worker = global_worker()
    worker._gcs_call(
        "KvPut",
        {"key": REQUEST_KEY, "value": json.dumps(bundles or []).encode()},
    )


def get_requested_resources(gcs_kv_get) -> list[dict]:
    """Parse the stored floor (used by the reconciler)."""
    blob = gcs_kv_get(REQUEST_KEY)
    if not blob:
        return []
    try:
        return json.loads(blob)
    except ValueError:
        return []
