"""Typed instance lifecycle + stuck-instance reconciliation (autoscaler v2).

Equivalent of the reference's ``python/ray/autoscaler/v2/instance_manager/``
(``common.py`` InstanceUtil state machine, ``reconciler.py``
``_handle_stuck_instances``): every cloud node the autoscaler manages is a
typed ``Instance`` moving through an explicit FSM

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                 |              |            |
                 v              v            v
        ALLOCATION_FAILED   TERMINATING -> TERMINATED

with per-state timestamps, validated transitions, bounded allocation
retries, and a reconcile pass that repairs stuck instances: requests the
cloud never fulfilled, nodes whose raylet never registered, and
terminations the cloud ignored. The v1-style dict provider "knows" none
of this — these are exactly the lifecycle edge cases the v2 model exists
for (VERDICT round-3 missing #4).

``InstanceManager`` duck-types ``NodeProvider`` (create/terminate/list/
node_id_of), so ``Autoscaler(provider=InstanceManager(real_provider))``
gains the lifecycle without changes.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

# ------------------------------------------------------------------ states
QUEUED = "QUEUED"                        # decided, not yet asked of the cloud
REQUESTED = "REQUESTED"                  # create_node issued
ALLOCATED = "ALLOCATED"                  # cloud lists the node
RAY_RUNNING = "RAY_RUNNING"              # raylet registered with the GCS
TERMINATING = "TERMINATING"              # terminate_node issued
TERMINATED = "TERMINATED"                # gone from the cloud listing
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # create failed / timed out

_TRANSITIONS: dict[str, set[str]] = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, RAY_RUNNING, ALLOCATION_FAILED, QUEUED, TERMINATING},
    ALLOCATED: {RAY_RUNNING, TERMINATING, TERMINATED},
    RAY_RUNNING: {TERMINATING, TERMINATED},
    TERMINATING: {TERMINATED},
    TERMINATED: set(),
    ALLOCATION_FAILED: {QUEUED, TERMINATED},
}


@dataclass
class Instance:
    instance_id: str                 # manager-scoped id
    node_type: str
    state: str = QUEUED
    cloud_instance_id: str = ""      # provider id once REQUESTED
    node_id: str = ""                # GCS node id once RAY_RUNNING
    retries: int = 0
    resources: dict = field(default_factory=dict)  # shape for retries
    history: list = field(default_factory=list)  # [(state, ts)]

    def __post_init__(self):
        if not self.history:
            self.history = [(self.state, time.time())]

    def since(self) -> float:
        """Seconds in the current state."""
        return time.time() - self.history[-1][1]


class InvalidTransition(RuntimeError):
    pass


class InstanceManager:
    """Typed lifecycle around a ``NodeProvider``; also IS a NodeProvider."""

    def __init__(
        self,
        provider,
        *,
        request_timeout_s: float = 300.0,
        ray_boot_timeout_s: float = 600.0,
        terminate_timeout_s: float = 300.0,
        max_allocation_retries: int = 3,
        replace_preempted: bool = True,
    ):
        self.provider = provider
        self.request_timeout_s = request_timeout_s
        self.ray_boot_timeout_s = ray_boot_timeout_s
        self.terminate_timeout_s = terminate_timeout_s
        self.max_allocation_retries = max_allocation_retries
        # Spot preemption handling: providers that surface
        # ``preemption_notices()`` (GCE spot reclaim, the local harness)
        # get their preempted instances terminated AND replaced with a
        # same-shape launch in the same reconcile round.
        self.replace_preempted = replace_preempted
        self._lock = threading.Lock()
        self._instances: dict[str, Instance] = {}
        self._by_cloud_id: dict[str, str] = {}
        self._counter = itertools.count(1)
        # GCS nodes alive before we managed anything (the head, manually
        # started nodes): never claimable by _match_gcs.
        self._preexisting: set[str] | None = None

    # ----------------------------------------------------------- transitions
    def _transition(self, inst: Instance, to: str) -> None:
        if to not in _TRANSITIONS[inst.state]:
            raise InvalidTransition(f"{inst.instance_id}: {inst.state} -> {to}")
        logger.info("instance %s (%s): %s -> %s",
                    inst.instance_id, inst.node_type, inst.state, to)
        inst.state = to
        inst.history.append((to, time.time()))

    # -------------------------------------------------- NodeProvider surface
    def create_node(self, node_type: str, resources: dict) -> str:
        """QUEUED -> REQUESTED immediately (the queue exists so retries and
        reconcile-driven launches share one path)."""
        with self._lock:
            inst = Instance(f"inst-{next(self._counter)}", node_type,
                            resources=dict(resources or {}))
            self._instances[inst.instance_id] = inst
            self._request_locked(inst, inst.resources)
            return inst.cloud_instance_id or inst.instance_id

    def _request_locked(self, inst: Instance, resources: dict) -> None:
        self._transition(inst, REQUESTED)
        try:
            cloud_id = self.provider.create_node(inst.node_type, resources)
        except Exception as e:
            logger.warning("allocation of %s failed: %s", inst.instance_id, e)
            self._transition(inst, ALLOCATION_FAILED)
            return
        inst.cloud_instance_id = cloud_id
        self._by_cloud_id[cloud_id] = inst.instance_id

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            iid = self._by_cloud_id.get(instance_id, instance_id)
            inst = self._instances.get(iid)
            if inst is None or inst.state in (TERMINATING, TERMINATED):
                return
            self._transition(inst, TERMINATING)
        try:
            self.provider.terminate_node(inst.cloud_instance_id or instance_id)
        except Exception as e:
            logger.warning("terminate of %s failed (reconcile will retry): %s",
                           inst.instance_id, e)

    def non_terminated_nodes(self) -> dict[str, str]:
        return self.provider.non_terminated_nodes()

    def node_id_of(self, instance_id: str) -> str | None:
        with self._lock:
            iid = self._by_cloud_id.get(instance_id, instance_id)
            inst = self._instances.get(iid)
            if inst is not None and inst.node_id:
                return inst.node_id
        return self.provider.node_id_of(instance_id)

    # ------------------------------------------------------------- reconcile
    def reconcile(self, gcs_nodes: list[dict] | None = None) -> dict[str, int]:
        """One reconciliation round: sync states with the cloud listing and
        the GCS node table, then repair stuck instances. Returns a count of
        repairs by kind (observability + tests)."""
        listing = self.provider.non_terminated_nodes()
        alive = {}
        for n in gcs_nodes or []:
            if n.get("state") == "ALIVE":
                alive[n["node_id"]] = n
        if self._preexisting is None:
            self._preexisting = set(alive)
        repairs = {"allocation_retried": 0, "allocation_failed": 0,
                   "ray_boot_timeout": 0, "terminate_reissued": 0,
                   "preempt_replaced": 0}
        notices: dict[str, str] = {}
        if self.replace_preempted:
            notices_fn = getattr(self.provider, "preemption_notices", None)
            if notices_fn is not None:
                try:
                    notices = dict(notices_fn() or {})
                except Exception:
                    notices = {}
        with self._lock:
            claimed = {i.cloud_instance_id for i in self._instances.values()
                       if i.cloud_instance_id}
            # Spot preemptions first: the cloud is reclaiming these
            # slices — confirm the terminate and queue a same-shape
            # replacement BEFORE the per-state pass, so the replacement
            # request lands in this same round.
            if notices:
                for inst in list(self._instances.values()):
                    if inst.cloud_instance_id not in notices:
                        continue
                    if inst.state not in (REQUESTED, ALLOCATED, RAY_RUNNING):
                        continue
                    logger.warning(
                        "instance %s (%s) preempted by the cloud: "
                        "terminating + requesting replacement",
                        inst.instance_id, inst.node_type)
                    repairs["preempt_replaced"] += 1
                    self._transition(inst, TERMINATING)
                    try:
                        self.provider.terminate_node(inst.cloud_instance_id)
                    except Exception:
                        pass
                    ack = getattr(self.provider, "ack_preemption", None)
                    if ack is not None:
                        try:
                            ack(inst.cloud_instance_id)
                        except Exception:
                            pass
                    replacement = Instance(
                        f"inst-{next(self._counter)}", inst.node_type,
                        resources=dict(inst.resources))
                    self._instances[replacement.instance_id] = replacement
                    self._request_locked(replacement, replacement.resources)
                    claimed.add(replacement.cloud_instance_id)
            for inst in list(self._instances.values()):
                if inst.state == REQUESTED:
                    if inst.cloud_instance_id not in listing:
                        # Identityless provider (e.g. KubeRay: create_node
                        # returns a synthetic launch id; the operator names
                        # the replica): ADOPT an unclaimed listed node of
                        # the same type — without this, every successful
                        # launch would read as an allocation failure and
                        # each "retry" would scale up ANOTHER real slice.
                        for cid, ctype in listing.items():
                            if ctype == inst.node_type and cid not in claimed:
                                self._by_cloud_id.pop(inst.cloud_instance_id, None)
                                inst.cloud_instance_id = cid
                                self._by_cloud_id[cid] = inst.instance_id
                                claimed.add(cid)
                                break
                    if inst.cloud_instance_id in listing:
                        self._transition(inst, ALLOCATED)
                        continue  # one transition per round (deterministic)
                    elif inst.cloud_instance_id == "" or inst.since() > self.request_timeout_s:
                        # Cloud never surfaced it (stockout / quota / lost
                        # call): fail, and retry with backoff-by-count.
                        if inst.state == REQUESTED:
                            self._transition(inst, ALLOCATION_FAILED)
                if inst.state == ALLOCATION_FAILED:
                    if inst.retries < self.max_allocation_retries:
                        inst.retries += 1
                        repairs["allocation_retried"] += 1
                        self._transition(inst, QUEUED)
                        self._request_locked(inst, inst.resources)
                    else:
                        repairs["allocation_failed"] += 1
                        self._transition(inst, TERMINATED)
                    continue
                if inst.state == ALLOCATED:
                    node_id = self.provider.node_id_of(inst.cloud_instance_id)
                    matched = node_id if node_id in alive else self._match_gcs(inst, alive)
                    if matched is not None:
                        inst.node_id = matched
                        self._transition(inst, RAY_RUNNING)
                    elif inst.cloud_instance_id not in listing:
                        self._transition(inst, TERMINATED)  # died while booting
                    elif inst.since() > self.ray_boot_timeout_s:
                        # Node exists but the raylet never registered
                        # (image/network broken): replace it.
                        repairs["ray_boot_timeout"] += 1
                        self._transition(inst, TERMINATING)
                        try:
                            self.provider.terminate_node(inst.cloud_instance_id)
                        except Exception:
                            pass
                    continue
                if inst.state == RAY_RUNNING:
                    if inst.cloud_instance_id not in listing:
                        self._transition(inst, TERMINATED)
                    continue
                if inst.state == TERMINATING:
                    if inst.cloud_instance_id not in listing:
                        self._transition(inst, TERMINATED)
                    elif inst.since() > self.terminate_timeout_s:
                        # The cloud ignored the delete: re-issue it.
                        repairs["terminate_reissued"] += 1
                        inst.history.append((TERMINATING, time.time()))
                        try:
                            self.provider.terminate_node(inst.cloud_instance_id)
                        except Exception:
                            pass
        return repairs

    def _match_gcs(self, inst: Instance, alive: dict) -> str | None:
        """Match an ALLOCATED instance to a GCS node when the provider has
        no identity mapping: claim an alive node no other instance owns."""
        owned = {i.node_id for i in self._instances.values() if i.node_id}
        for node_id in alive:
            if node_id not in owned and node_id not in (self._preexisting or set()):
                return node_id
        return None

    # -------------------------------------------------------------- queries
    def instances(self, *states: str) -> list[Instance]:
        with self._lock:
            if not states:
                return list(self._instances.values())
            return [i for i in self._instances.values() if i.state in states]
