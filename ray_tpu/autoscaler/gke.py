"""GKE/KubeRay-style NodeProvider: TPU node pools on Kubernetes.

Equivalent of the reference's KubeRay provider
(``python/ray/autoscaler/_private/kuberay/node_provider.py`` —
``BatchingNodeProvider`` semantics: the autoscaler PATCHes the RayCluster
custom resource's ``workerGroupSpecs[i].replicas`` /
``scaleStrategy.workersToDelete`` and the operator actuates pods), with
the TPU specifics GKE adds: a worker group with ``numOfHosts > 1`` is a
MULTI-HOST slice whose pods share a ``replicaIndex`` label — one
autoscaler "node" is one REPLICA (the slice-atomic unit), never a single
pod of it.

The Kubernetes API transport is injectable: in-cluster it reads the
service-account token and talks to ``KUBERNETES_SERVICE_HOST``; tests
drive the full provider + reconciler against a fake transport (zero
egress here).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any

from .node_provider import NodeProvider

logger = logging.getLogger(__name__)

GROUP_LABEL = "ray.io/group"          # worker group == autoscaler node type
KIND_LABEL = "ray.io/node-type"       # head | worker
REPLICA_INDEX_LABEL = "replicaIndex"  # GKE multi-host slice replica id

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubernetesTransport:
    """In-cluster API access via the pod service account."""

    def __init__(self, timeout_s: float = 60.0):
        self._timeout = timeout_s
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT_HTTPS", "443")
        self._base = f"https://{host}:{port}"

    def _token(self) -> str:
        try:
            with open(os.path.join(_SA_DIR, "token")) as f:
                return f.read().strip()
        except OSError as e:
            raise RuntimeError(
                "GkeTpuNodeProvider needs an in-cluster service account "
                "(or inject a transport)") from e

    def request(self, method: str, path: str, body: dict | None = None) -> dict:
        import ssl
        import urllib.request

        ctx = ssl.create_default_context(cafile=os.path.join(_SA_DIR, "ca.crt"))
        headers = {
            "Authorization": f"Bearer {self._token()}",
            "Content-Type": ("application/json-patch+json" if method == "PATCH"
                             else "application/json"),
        }
        req = urllib.request.Request(
            self._base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method, headers=headers)
        with urllib.request.urlopen(req, timeout=self._timeout, context=ctx) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}


class GkeTpuNodeProvider(NodeProvider):
    """Scale TPU worker groups of a RayCluster CR (KubeRay semantics).

    A "node" is one worker-group REPLICA: for a multi-host TPU group
    (``numOfHosts`` > 1) that is the whole slice — its pods carry the same
    ``replicaIndex`` and are created/deleted together by the operator,
    matching the slice-atomic scheduling the raylet's
    ``TPU-{type}-head`` resource assumes."""

    def __init__(
        self,
        namespace: str,
        cluster_name: str,
        *,
        transport: Any = None,
        crd_version: str = "v1",
    ):
        self.namespace = namespace
        self.cluster_name = cluster_name
        self.transport = transport or KubernetesTransport()
        self._crd = crd_version
        self._lock = threading.Lock()
        # replica-name -> group, for nodes we created this process (the CR
        # itself is the durable source of truth; this is only a hint).
        self._counter = 0

    # ------------------------------------------------------------- CR access
    def _cr_path(self) -> str:
        return (f"/apis/ray.io/{self._crd}/namespaces/{self.namespace}"
                f"/rayclusters/{self.cluster_name}")

    def _pods_path(self) -> str:
        return (f"/api/v1/namespaces/{self.namespace}/pods"
                f"?labelSelector=ray.io/cluster={self.cluster_name}")

    def _get_cr(self) -> dict:
        return self.transport.request("GET", self._cr_path())

    def _group_index(self, cr: dict, group: str) -> int:
        groups = cr["spec"].get("workerGroupSpecs") or []
        for i, g in enumerate(groups):
            if g.get("groupName") == group:
                return i
        raise ValueError(
            f"worker group {group!r} not in RayCluster {self.cluster_name} "
            f"(groups: {[g.get('groupName') for g in groups]})")

    # ------------------------------------------------------ NodeProvider API
    def create_node(self, node_type: str, resources: dict) -> str:
        """Scale the group up by one replica (the operator creates the
        pod(s)); returns a synthetic replica id resolved against pod
        listings by group membership."""
        cr = self._get_cr()
        idx = self._group_index(cr, node_type)
        replicas = int(cr["spec"]["workerGroupSpecs"][idx].get("replicas") or 0)
        self.transport.request("PATCH", self._cr_path(), [
            {"op": "replace",
             "path": f"/spec/workerGroupSpecs/{idx}/replicas",
             "value": replicas + 1},
        ])
        with self._lock:
            self._counter += 1
            return f"{self.cluster_name}-{node_type}-launch-{self._counter}"

    def terminate_node(self, instance_id: str) -> None:
        """Scale down via ``workersToDelete`` so the operator removes THIS
        replica, not an arbitrary one (the KubeRay precise-scale-down
        contract). Only LIVE replica ids are accepted: decrementing
        replicas for an unknown name would make the operator delete an
        arbitrary (possibly busy) replica instead."""
        replicas_live = self._replicas()
        if instance_id not in replicas_live:
            logger.warning(
                "terminate of %s ignored: not a live replica (synthetic "
                "launch ids resolve to replica ids once the operator "
                "creates the pods)", instance_id)
            return
        group = replicas_live[instance_id]
        cr = self._get_cr()
        idx = self._group_index(cr, group)
        spec = cr["spec"]["workerGroupSpecs"][idx]
        replicas = int(spec.get("replicas") or 0)
        # Prune confirmed deletions (no longer live) so workersToDelete
        # doesn't grow forever, then add this one.
        to_delete = [
            w for w in ((spec.get("scaleStrategy") or {}).get("workersToDelete") or [])
            if w in replicas_live
        ]
        if instance_id not in to_delete:
            to_delete.append(instance_id)
        self.transport.request("PATCH", self._cr_path(), [
            {"op": "replace",
             "path": f"/spec/workerGroupSpecs/{idx}/replicas",
             "value": max(0, replicas - 1)},
            {"op": "replace",
             "path": f"/spec/workerGroupSpecs/{idx}/scaleStrategy",
             "value": {"workersToDelete": to_delete}},
        ])

    def _replicas(self) -> dict[str, str]:
        """replica id -> group from live pods. A multi-host slice's pods
        collapse into ONE entry keyed by (group, replicaIndex)."""
        pods = self.transport.request("GET", self._pods_path()).get("items", [])
        out: dict[str, str] = {}
        for pod in pods:
            meta = pod.get("metadata") or {}
            labels = meta.get("labels") or {}
            if labels.get(KIND_LABEL) != "worker":
                continue
            phase = (pod.get("status") or {}).get("phase")
            if phase in ("Succeeded", "Failed"):
                continue
            group = labels.get(GROUP_LABEL, "unknown")
            replica = labels.get(REPLICA_INDEX_LABEL) or meta.get("name", "")
            out[replica] = group
        return out

    def non_terminated_nodes(self) -> dict[str, str]:
        return self._replicas()

    def node_id_of(self, instance_id: str) -> str | None:
        return None  # the raylet self-registers; reconciler matches by expiry
