"""Always-warm fleet bench: standby promotion vs cold start, weight
broadcast parity, and goodput through a traffic step.

ISSUE 19 acceptance cells, runnable standalone (``python -m ray_tpu.cli
bench fleet``) or inside ``bench.py``:

  * ``serve_replica_cold_start_s`` — full replica cold start: weight
    init + engine construction + the first token (prefill/decode
    compile included), the price the SLO pays without a warm pool.
  * ``serve_replica_promote_s`` — standby promotion on the SAME engine:
    weights restored host→device onto a warm compile cache, then the
    first token. ``serve_replica_promote_speedup`` = cold / promote,
    targeting ≥ 10×.
  * ``fleet_broadcast_parity`` — 1.0 iff TWO concurrent readers of one
    ``WeightBroadcastSource`` stream both reconstruct a pytree whose
    content fingerprint is byte-identical to the donor's (the fan-out
    weight-delivery path vs direct load).
  * ``fleet_goodput_frac_step`` — fraction of requests completing with
    a 200 inside the latency budget while offered load STEPS to 10× the
    measured solo rate against a 1-running + 1-standby deployment; the
    step is what the predictive/standby machinery exists to absorb.

CPU-sandbox honest: debug presets, byte tokenizer, no wall-clock SLO
claims — the promote speedup compares two timings on the same machine
and the parity/goodput cells are scale-free. Set
``RAY_TPU_BENCH_SKIP_FLEET=1`` to leave ``*_skipped`` markers that
``bench_check`` honors.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

SKIP_MARKERS = {
    "fleet_skipped": True,
    "serve_replica_cold_start_s_skipped": True,
    "serve_replica_promote_s_skipped": True,
    "serve_replica_promote_speedup_skipped": True,
}


def _pct(sorted_vals: list[float], q: float) -> float:
    return sorted_vals[max(0, int(len(sorted_vals) * q) - 1)]


def _engine_cells(out: dict) -> None:
    """Cold start vs standby promotion, plus broadcast parity — straight
    off the engine so the comparison isolates what the fleet changes:
    where the weights come from and whether the compile cache is warm."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ray_tpu.llm.engine import InferenceEngine, Request
    from ray_tpu.llm.weights import (WeightBroadcastSource,
                                     params_fingerprint,
                                     receive_weight_stream)
    from ray_tpu.models.llama import PRESETS, init_params

    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    def first_token(eng) -> None:
        r = Request(f"warm{eng.metrics['weights_promoted']}-{time.time_ns()}",
                    list(prompt), max_new_tokens=1)
        eng.add_request(r)
        while not r.done:
            eng.step()

    # ---- cold start: everything a fresh replica pays — weight init,
    # engine construction, and the first token's XLA compiles.
    t0 = time.perf_counter()
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64,
                          enable_prefix_cache=False)
    first_token(eng)
    cold_s = time.perf_counter() - t0
    out["serve_replica_cold_start_s"] = round(cold_s, 4)

    # ---- standby promotion: same engine demoted to host RAM (compile
    # cache stays warm), then promoted and serving its first token.
    assert eng.demote_weights_to_host()["ok"]
    t0 = time.perf_counter()
    assert eng.promote_weights_from_host()["ok"]
    first_token(eng)
    promote_s = time.perf_counter() - t0
    out["serve_replica_promote_s"] = round(promote_s, 4)
    out["serve_replica_promote_speedup"] = round(
        cold_s / max(1e-9, promote_s), 2)

    # ---- broadcast parity: two concurrent readers of one source must
    # reconstruct the donor's exact bytes (content fingerprints equal).
    want = params_fingerprint(eng.executor.params)
    src = WeightBroadcastSource(eng.executor.params, model="fleet-bench",
                                n_readers=2)
    results: list[dict | None] = [None, None]

    def read(i: int) -> None:
        results[i] = receive_weight_stream(src.address, timeout_s=60.0)

    threads = [threading.Thread(target=read, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    src.join(timeout=10)
    ok = all(r is not None and r["complete"] and r["fingerprint"] == want
             and params_fingerprint(r["params"]) == want for r in results)
    out["fleet_broadcast_parity"] = 1.0 if ok else 0.0
    out["fleet_broadcast_bytes_cfg"] = results[0]["bytes"] if results[0] else 0


def _one_request(addr: str, route: str, prompt: str, max_tokens: int,
                 client_timeout: float) -> dict:
    """One streaming completion; returns {"status", "wall_s"}."""
    body = {"prompt": prompt, "max_tokens": max_tokens, "stream": True}
    req = urllib.request.Request(addr + route + "/v1/completions",
                                 data=json.dumps(body).encode(),
                                 headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    out = {"status": "200", "wall_s": None}
    try:
        with urllib.request.urlopen(req, timeout=client_timeout) as resp:
            for _ in resp:
                pass
    except urllib.error.HTTPError as e:
        out["status"] = str(e.code)
        try:
            e.read()
        except Exception:
            pass
    except Exception as e:
        out["status"] = type(e).__name__
    out["wall_s"] = time.perf_counter() - t0
    return out


def _step_cells(out: dict, step_s: float) -> None:
    """Goodput through a 10× offered-rate step against a deployment kept
    at 1 running + 1 standby replica: the autoscaler's breach promotes
    the standby (one host→device transfer) instead of paying a cold
    start mid-step."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    max_tokens = 8
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    serve.run(
        build_llm_app(
            "debug-128", max_slots=4, max_len=128, page_size=16,
            prefill_chunk_size=64, num_replicas=1,
            max_ongoing_requests=4, max_queued_requests=16,
            autoscaling_config={
                "min_replicas": 1, "max_replicas": 2,
                "mode": "latency_slo", "target_ttft_ms": 400.0,
                "latency_window_s": 5.0, "breach_cycles": 1,
                "upscale_delay_s": 0.0, "downscale_delay_s": 3600.0,
                "standby_replicas": 1, "predictive": True,
                "predictive_horizon_s": 5.0,
            }),
        name="fleet", route_prefix="/fleet", timeout_s=360.0)
    addr = serve.http_address()
    route = "/fleet"
    try:
        def prompt_for(tag: str, i: int) -> str:
            return f"req {tag}-{i}: " + "abcdefgh" * (4 + i % 3)

        # Wait for the standby pool to warm (the controller starts the
        # extra replica and demotes it once RUNNING).
        warm_deadline = time.time() + 180.0
        standby_warm = False
        def dep_status() -> dict:
            return next(iter((serve.status().get("fleet") or {}).values()),
                        None) or {}

        while time.time() < warm_deadline:
            if dep_status().get("standby_replicas", 0) >= 1:
                standby_warm = True
                break
            time.sleep(0.5)
        out["fleet_standby_warm_cfg"] = bool(standby_warm)

        # Solo phase: closed-loop trickle to measure this machine's
        # single-replica service rate (and warm the XLA cache).
        solo = [_one_request(addr, route, prompt_for("solo", i),
                             max_tokens, 120.0) for i in range(8)]
        solo_walls = sorted(r["wall_s"] for r in solo
                            if r["status"] == "200")
        if not solo_walls:
            raise RuntimeError("solo phase served 0 requests")
        solo_rps = 1.0 / max(1e-3, sum(solo_walls) / len(solo_walls))
        budget_s = 6.0 * _pct(solo_walls, 0.5) + 2.0

        # Step phase: offered rate jumps to 10× the solo service rate,
        # open-loop paced so slow responses can't throttle the offer.
        offered_rps = 10.0 * solo_rps
        n_offered = min(48, max(12, int(offered_rps * step_s)))
        results: list[dict | None] = [None] * n_offered
        t0 = time.perf_counter()

        def fire(i: int) -> None:
            delay = t0 + i / offered_rps - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            results[i] = _one_request(addr, route, prompt_for("step", i),
                                      max_tokens, 120.0)

        threads = [threading.Thread(target=fire, args=(i,), daemon=True)
                   for i in range(n_offered)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        done = [r for r in results if r is not None]
        good = sum(1 for r in done if r["status"] == "200"
                   and r["wall_s"] is not None and r["wall_s"] <= budget_s)
        out["fleet_goodput_frac_step"] = round(good / max(1, len(done)), 4)
        out["fleet_step_offered_cfg"] = n_offered
        dep = dep_status()
        promote = dep.get("last_promote") or {}
        out["fleet_step_promote_path_cfg"] = promote.get("path") or ""
        out["fleet_step_running_cfg"] = dep.get("running_replicas")
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass


def run_fleet_bench(step_s: float | None = None) -> dict:
    if os.environ.get("RAY_TPU_BENCH_SKIP_FLEET") == "1":
        return dict(SKIP_MARKERS)
    step_s = step_s or float(os.environ.get("RAY_TPU_FLEET_STEP_S", "6"))
    out: dict = {}
    _engine_cells(out)
    _step_cells(out, step_s)
    return out


if __name__ == "__main__":
    print(json.dumps(run_fleet_bench()))
