"""Ulysses attention: all-to-all sequence parallelism.

Green-field like ring attention (the reference has no sequence/context
parallelism of its own, SURVEY.md §5.7); this is the DeepSpeed-Ulysses
strategy: sequence-sharded Q/K/V are reshuffled over the ``sp`` axis
with ONE all-to-all so each device holds the FULL sequence for a
subset of heads, runs the ordinary (Pallas flash) attention locally,
and a second all-to-all restores sequence sharding. Two collectives per
attention vs ring's (n-1) ppermute hops — cheaper when head count
divides well and the sequence fits one device's HBM; ring wins when the
full sequence per device does not fit. Both are selectable via
``LlamaConfig.attn_impl`` ("ulysses" | "ring").

Call inside ``shard_map`` with the sequence axis mapped to ``sp``.
"""

from __future__ import annotations

from jax import lax

from .attention import flash_attention


def ulysses_attention(
    q,
    k,
    v,
    *,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: float | None = None,
):
    """q [B,Hq,Sl,D], k/v [B,Hkv,Sl,D] — Sl is the per-device sequence
    chunk (chunks in ring order across the axis). Hq must be divisible
    by the axis size; Hkv must divide it or be divisible by it (smaller
    Hkv is replicated up). Returns the local output chunk [B,Hq,Sl,D]."""
    import jax.numpy as jnp

    n = lax.axis_size(axis)
    hq, hkv = q.shape[1], k.shape[1]
    if hq % n:
        raise ValueError(
            f"ulysses needs query heads divisible by the sp axis: "
            f"Hq={hq}, sp={n}")
    if hkv % n:
        # GQA with fewer KV heads than sp ranks: replicate KV heads up to
        # the axis size (the standard Ulysses workaround — ships
        # replicated KV through the all-to-all; ring attention avoids
        # this and is preferable at extreme GQA ratios).
        if n % hkv:
            raise ValueError(
                f"ulysses needs Hkv to divide (or be divisible by) sp: "
                f"Hkv={hkv}, sp={n}")
        k = jnp.repeat(k, n // hkv, axis=1)
        v = jnp.repeat(v, n // hkv, axis=1)
    # heads -> devices, sequence gathered: [B, H/n, Sl*n, D]
    q = lax.all_to_all(q, axis, split_axis=1, concat_axis=2, tiled=True)
    k = lax.all_to_all(k, axis, split_axis=1, concat_axis=2, tiled=True)
    v = lax.all_to_all(v, axis, split_axis=1, concat_axis=2, tiled=True)
    o = flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    # back: sequence -> devices, heads gathered
    return lax.all_to_all(o, axis, split_axis=2, concat_axis=1, tiled=True)
