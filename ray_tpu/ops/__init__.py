"""TPU compute ops: Pallas kernels and the JAX ops the models are built on.

The hot paths (attention) are Pallas TPU kernels; everything elementwise
is left to XLA fusion. Sequence/context parallelism (ring attention) is
green-field — the reference has none (SURVEY.md §5.7).
"""

from .attention import flash_attention, mha_reference
from .ring_attention import ring_attention
from .ulysses import ulysses_attention
from .norms import rms_norm
from .rope import apply_rope, rope_frequencies

__all__ = [
    "flash_attention",
    "mha_reference",
    "ring_attention",
    "ulysses_attention",
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
]
