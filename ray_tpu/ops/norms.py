"""Normalization ops. Plain jnp — XLA fuses these into neighbors on TPU;
a hand-written kernel would only duplicate that fusion."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, *, eps: float = 1e-6):
    """Llama-style RMSNorm, f32 statistics regardless of input dtype."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)
