"""Paged-attention decode kernel, v2 "staging-buffer" design (Pallas TPU).

The framework's native answer to the decode kernel the reference buys
from vLLM (``python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py`` — the engine all of ``ray.llm`` delegates token
generation to). One decode step reads, per sequence, ONLY the KV pages
that hold live context: the sequence's block table is scalar-prefetched
into SMEM, and the kernel's input index maps walk it so the pipelined
HBM→VMEM copies fetch just the live pages, accumulating flash-style
online softmax per page block. HBM traffic per step is
``O(live_tokens)`` per SLOT — a dense gather pays the batch-max live
context for EVERY slot.

Why v2. The v1 kernel wrote the current token's K/V into the pool from
INSIDE the kernel through ``input_output_aliases`` — the only way to
mutate a loop-carried pool next to an opaque custom call without XLA
materializing a pool-sized copy per step. But the same pool buffer was
also a READ operand ``ppb`` more times (Mosaic can't DMA-slice
unaligned minor dims, so discontiguous pages ride separate BlockSpec
operands), and XLA cannot alias a buffer that is simultaneously donated
to an output and read through other operands: it inserted the defensive
copies anyway (~60 ms/step on a 1B model's 2 GB pool), and the kernel
lost to its own dense fallback.

v2 removes the conflict instead of fighting it:

  * **The pool is strictly READ-ONLY across the whole K-step fused
    dispatch.** No aliasing, no in-kernel writes, nothing for XLA to
    defend — the donated pool buffer passes through the decode scan
    untouched and un-copied.
  * **New tokens accumulate in a small staging carry**
    ``[L, slots, KH, SC, D]`` (SC = fused steps, padded to the sublane
    tile — KBs, not GBs). Step ``j`` writes each slot's fresh K/V at
    staging row ``j`` with a plain (cheap, tiny) XLA scatter; the
    kernel folds rows ``[0, j]`` into its online softmax as a SECOND KV
    source after the pool pages.
  * **ONE batched pool scatter per dispatch** (not per step) commits
    the staging buffer back at the dispatch boundary — by then the scan
    that read the pool has completed, so the donated buffer is updated
    in place.

Layout contract (matches ``llm/model.py``):

    k_pages / v_pages : [L, num_pages, KH, page_size, D]  (stacked pool;
                        a single-layer [num_pages, ...] pool is promoted)
    block_tables      : [slots, max_pages_per_seq] int32
    pos               : [slots] int32 — attend over [0, pos] inclusive
    q                 : [slots, KH, G, D]  (G = q heads per kv head)
    k_stage / v_stage : [Ls, slots, KH, SC, D] — staged tokens; row i of
                        slot s holds position ``base_s + i`` where
                        ``base_s = pos_s - stage_idx`` (the pool holds
                        [0, base_s) only)

Kernel structure:
  * grid = (slots, page_blocks), trailing axis sequential on-core so
    the f32 online-softmax state (m / l / acc scratch) carries across
    the page blocks of one sequence.
  * A grid step covers ``ppb`` pages (~256 tokens). Discontiguous pages
    can't ride one BlockSpec, so the pool is passed ``ppb`` times, each
    input's index map selecting one page of the block —
    auto-pipelining then double-buffers all of them. (Manual
    ``make_async_copy`` from HBM needs 128-aligned minor dims, which
    head_dim 64 models violate; pipelined copies don't.)
  * Dead blocks — past the live page count — clamp their index maps to
    the last live page. Pallas elides copies whose block index repeats,
    and ``pl.when`` skips the compute, so dead blocks cost neither
    bandwidth nor FLOPs.
  * The staging fold runs at the FINAL grid block: scores against the
    slot's [SC, D] staging rows, rows past ``stage_idx`` masked, then
    the normalize. Row ``stage_idx`` is the current token (always
    attended), so pos == 0 — where no pool block computes and
    m = -inf, l = 0 — still normalizes to exactly the staged value.
  * GQA without K/V replication: per kv head, q is [G, D] against the
    head's [T, D] page block (static loop over KH — decode is
    bandwidth-bound; MXU utilization is irrelevant here).

Off-TPU the kernel runs in interpreter mode (tests); the engine keeps
the dense path as the CPU default since interpret-mode decode is slow
(``llm/executor.resolve_attention_impl``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Staging rows are the kernel block's sublane dim: keep them a multiple
# of the bf16 tile (16) so one padded size serves every pool dtype.
_STAGE_TILE = 16


def stage_rows(n_steps: int) -> int:
    """Padded staging-row count for an ``n_steps``-deep fused dispatch."""
    return max(_STAGE_TILE, -(-n_steps // _STAGE_TILE) * _STAGE_TILE)


def _decode_kernel(
    bt_ref,      # [slots, max_pages] int32 (SMEM, scalar-prefetched)
    base_ref,    # [slots] int32 — pool holds [0, base) per slot (SMEM)
    sl_ref,      # [1] int32 — staged rows [0, sl] are live (SMEM)
    l_ref,       # [1] int32 layer index (SMEM; consumed by index maps)
    q_ref,       # [1, KH, Gp, D] VMEM block
    ks_ref,      # [1, 1, KH, SC, D] this slot's staged K rows
    vs_ref,      # [1, 1, KH, SC, D] this slot's staged V rows
    *refs,       # ppb k-page refs, ppb v-page refs ([1, 1, KH, page, D]),
                 # then the output o, then scratch m/l/acc
    kh: int,
    page_size: int,
    ppb: int,
    n_blocks: int,
    scale: float,
):
    k_refs = refs[:ppb]
    v_refs = refs[ppb:2 * ppb]
    o_ref, m_ref, lsum_ref, acc_ref = refs[2 * ppb:]
    si = pl.program_id(0)
    bi = pl.program_id(1)
    base = base_ref[si]
    # The pool holds positions [0, base) — everything newer rides the
    # staging rows and is folded below.
    n_live_pages = jax.lax.div(base + page_size - 1, page_size)
    needed = bi * ppb < n_live_pages

    @pl.when(bi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        lsum_ref[...] = jnp.zeros_like(lsum_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(needed)
    def _compute():
        t = ppb * page_size
        gp = q_ref.shape[2]
        # Token liveness within the block: global position < base
        # (strict — newer positions live in the staging rows).
        t_pos = bi * t + jax.lax.broadcasted_iota(jnp.int32, (gp, t), 1)
        live = t_pos < base

        for h in range(kh):
            q = q_ref[0, h]                                   # [Gp, D]
            kb = jnp.concatenate([r[0, 0, h] for r in k_refs])  # [T, D]
            vb = jnp.concatenate([r[0, 0, h] for r in v_refs])
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [Gp, T]
            # lax.select, not jnp.where: jnp's scalar-broadcast wrapper
            # lowers to a closed_call that trips a lowering-cache
            # KeyError (jax 0.9.0) when this kernel sits in an outer scan.
            s = jax.lax.select(live, s, jnp.full_like(s, NEG_INF))
            m_prev = m_ref[h]                                 # [Gp, 128]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
            p = jnp.exp(s - m_new[:, :1])
            alpha = jnp.exp(m_prev - m_new)
            lsum_ref[h] = lsum_ref[h] * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=1, keepdims=True), lsum_ref[h].shape)
            acc_ref[h] = acc_ref[h] * alpha[:, :1] + jax.lax.dot(
                p.astype(vb.dtype), vb, preferred_element_type=jnp.float32)
            m_ref[h] = m_new

    @pl.when(bi == n_blocks - 1)
    def _final():
        # Fold the staging rows (positions [base, base + sl], the last
        # being the in-flight token — always attended), then normalize.
        # Covers base == 0 too: no pool block ran (m = -inf, l = 0) and
        # the output reduces to softmax over the staged rows alone.
        sl = sl_ref[0]
        sc = ks_ref.shape[3]
        gp = q_ref.shape[2]
        row = jax.lax.broadcasted_iota(jnp.int32, (gp, sc), 1)
        live = row <= sl
        for h in range(kh):
            q = q_ref[0, h]                                   # [Gp, D]
            ks = ks_ref[0, 0, h]                              # [SC, D]
            vs = vs_ref[0, 0, h]
            s = jax.lax.dot_general(
                q, ks, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [Gp, SC]
            s = jax.lax.select(live, s, jnp.full_like(s, NEG_INF))
            m_prev = m_ref[h]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
            p = jnp.exp(s - m_new[:, :1])
            alpha = jnp.exp(m_prev - m_new)
            lsum = lsum_ref[h] * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=1, keepdims=True), lsum_ref[h].shape)
            acc = acc_ref[h] * alpha[:, :1] + jax.lax.dot(
                p.astype(vs.dtype), vs, preferred_element_type=jnp.float32)
            o_ref[0, h] = (acc / lsum[:, :1]).astype(o_ref.dtype)


# NOTE: deliberately NOT @jax.jit-wrapped — a nested jit around a
# pallas_call inside an outer scan trips a lowering-cache KeyError in
# jax 0.9.0 ('closed_call' in cached_primitive_lowerings). Callers are
# always under jit themselves (decode_loop / decode_step).
def paged_decode_attention(
    q,
    k_pages,
    v_pages,
    block_tables,
    pos,
    k_cur=None,
    v_cur=None,
    *,
    page_size: int,
    pages_per_block: int | None = None,
    live_pages: int | None = None,
    layer=None,
    k_stage=None,
    v_stage=None,
    stage_idx=None,
    mesh=None,
    interpret: bool | None = None,
):
    """One decode step of attention over a read-only paged KV pool.

    q:            [slots, KH, G, D] — current-token queries, grouped by
                  kv head (``q.reshape(slots, KH, G, D)`` of the [H, D]
                  layout, matching ``llm/model.py``'s GQA grouping).
    k/v_pages:    [num_pages, KH, page_size, D] — one layer's pool — or
                  the FULL stacked pool [L, num_pages, KH, page_size, D]
                  with ``layer`` the (traced) layer index. Passing the
                  stacked pool lets the layer scan keep the pool in its
                  carry: the layer index rides the scalar prefetch into
                  the page index maps, so no [num_pages, ...] slice is
                  ever materialized. The pool is NEVER written here —
                  committing staged tokens back is the caller's
                  dispatch-boundary scatter (``llm/model.py``).
    block_tables: [slots, max_pages_per_seq] int32.
    pos:          [slots] int32 — attend over [0, pos] inclusive.

    Staging mode (the decode path): ``k_stage``/``v_stage``
    [Ls, slots, KH, SC, D] hold the tokens generated so far inside the
    current fused dispatch — row i of slot s is position
    ``pos_s - stage_idx + i`` — and ``stage_idx`` (traced scalar int32)
    says rows [0, stage_idx] are live, the last being the CURRENT
    token. The pool must hold [0, pos - stage_idx) only. ``Ls`` may be
    1 (per-layer staging) or the pool's L (layer-stacked staging
    indexed by ``layer``).

    Compat mode (kernel tests / one-off calls): without staging, the
    current token comes from ``k_cur``/``v_cur`` [slots, KH, D] (pool
    holds [0, pos)), or — when those are omitted too — is pulled back
    out of a pool that already holds position ``pos``. Both reduce to a
    single-row staging buffer internally.

    live_pages:   static upper bound on live POOL pages of ANY slot
                  (i.e. ``max(pos - stage_idx) // page_size + 1`` ≤
                  live_pages). Bounds the GRID, not just the copies:
                  without it, dead blocks still pay per-step pipeline
                  bookkeeping, so step count scales with pool capacity.
                  Callers should bucket it (powers of two) to bound
                  recompiles.

    mesh:         shard_map the kernel over the mesh's ``tp`` axis: the
                  pool/staging/q shard on their KV-head dim (the layout
                  ``llm/executor.py`` already gives them), each shard
                  runs the kernel on its local heads, and nothing is
                  gathered — attention is embarrassingly parallel over
                  KV heads. Manual over {"tp"} only, so other mesh axes
                  stay auto-partitioned. Requires ``KH %% tp == 0``
                  (enforced by the executor). Used by PURE-tp meshes
                  only: pp meshes — composed pp×tp included — call the
                  kernel with ``mesh=None`` from inside
                  ``pp_model.pp_decode_loop``'s own manual region
                  (flattened over {"pp","tp"} when tp composes), where
                  every operand is already a local shard.

    Returns [slots, KH, G, D] in q.dtype.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    squeeze_layer = k_pages.ndim == 4
    if squeeze_layer:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    layer = (jnp.zeros((1,), jnp.int32) if layer is None
             else jnp.asarray(layer, jnp.int32).reshape(1))
    n, kh, g, d = q.shape
    max_pages = block_tables.shape[1]
    if k_stage is not None:
        if k_cur is not None or stage_idx is None:
            raise ValueError("staging mode takes k_stage/v_stage/stage_idx "
                             "and no k_cur/v_cur")
        base = pos - jnp.asarray(stage_idx, jnp.int32)
        sl = jnp.asarray(stage_idx, jnp.int32).reshape(1)
    else:
        # Compat: single-row staging holding just the current token at
        # position ``pos``; the pool side masks strictly below it.
        base = pos
        sl = jnp.zeros((1,), jnp.int32)
        if k_cur is None:
            # Pool already holds position ``pos``: pull the token back
            # out so pool mask + staging fold give identical semantics.
            wp = jnp.take_along_axis(
                block_tables,
                jnp.minimum(pos // page_size, max_pages - 1)[:, None],
                axis=1)[:, 0]
            off = pos % page_size
            k_cur = k_pages[layer[0], wp, :, off]          # [slots, KH, D]
            v_cur = v_pages[layer[0], wp, :, off]
        k_stage = jnp.zeros((1, n, kh, _STAGE_TILE, d), k_pages.dtype
                            ).at[0, :, :, 0].set(k_cur.astype(k_pages.dtype))
        v_stage = jnp.zeros((1, n, kh, _STAGE_TILE, d), v_pages.dtype
                            ).at[0, :, :, 0].set(v_cur.astype(v_pages.dtype))
    stage_layers = k_stage.shape[0]
    sc = k_stage.shape[3]
    covered = max_pages if live_pages is None else min(live_pages, max_pages)
    # ~256 tokens of context per grid step: few enough steps that grid
    # overhead stays small, few enough inputs that VMEM stays bounded.
    if pages_per_block is None:
        pages_per_block = max(1, min(covered, 256 // page_size, 8))
    ppb = min(pages_per_block, covered)
    n_blocks = -(-covered // ppb)

    def _call(q, block_tables, base, sl, layer, k_stage, v_stage,
              k_pages, v_pages):
        # Shapes read here, not closed over: under shard_map this runs
        # per tp shard with the LOCAL KV-head count.
        n, kh, g, d = q.shape
        # Pad G to the f32 sublane tile (8) so scratch/compute rows are
        # aligned; padded q rows are zeros, their outputs sliced off.
        gp = -(-g // 8) * 8
        if gp != g:
            q = jnp.pad(q, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

        def page_index_map(j):
            # Page j of block bi for slot si; dead/overflow indices clamp
            # to the last live page so consecutive steps repeat the block
            # index and Pallas skips the copy. (Scalar-prefetch refs
            # arrive as trailing index-map args; lax ops, not jnp — see
            # closed_call note above.)
            def index_map(si, bi, bt_ref, base_ref, sl_ref, l_ref):
                n_live = jax.lax.div(base_ref[si] + page_size - 1, page_size)
                logical = jax.lax.max(
                    jax.lax.min(bi * ppb + j,
                                jax.lax.min(n_live, max_pages) - 1), 0)
                return l_ref[0], bt_ref[si, logical], 0, 0, 0
            return index_map

        def stage_map(si, bi, bt_ref, base_ref, sl_ref, l_ref):
            # Per-layer staging (Ls == 1) clamps the layer index to 0.
            return jax.lax.min(l_ref[0], stage_layers - 1), si, 0, 0, 0

        page_block = (1, 1, kh, page_size, d)
        kernel = functools.partial(
            _decode_kernel,
            kh=kh,
            page_size=page_size,
            ppb=ppb,
            n_blocks=n_blocks,
            scale=d ** -0.5,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n, n_blocks),
            in_specs=[
                pl.BlockSpec((1, kh, gp, d), lambda si, bi, *_: (si, 0, 0, 0)),
                pl.BlockSpec((1, 1, kh, sc, d), stage_map),
                pl.BlockSpec((1, 1, kh, sc, d), stage_map),
                *[pl.BlockSpec(page_block, page_index_map(j)) for j in range(ppb)],
                *[pl.BlockSpec(page_block, page_index_map(j)) for j in range(ppb)],
            ],
            out_specs=pl.BlockSpec((1, kh, gp, d),
                                   lambda si, bi, *_: (si, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((kh, gp, 128), jnp.float32),
                pltpu.VMEM((kh, gp, 128), jnp.float32),
                pltpu.VMEM((kh, gp, d), jnp.float32),
            ],
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n, kh, gp, d), q.dtype),
            interpret=interpret,
        )(block_tables, base, sl, layer,
          q, k_stage, v_stage,
          *([k_pages] * ppb), *([v_pages] * ppb))
        return out[:, :, :g] if gp != g else out

    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        # Manual over tp ONLY (other axes stay auto): every shard runs
        # the identical kernel on its KV-head slice of q/pool/staging —
        # no collectives, attention is independent per KV head. This is
        # what lifts the old "paged is single-device only" refusal.
        if not hasattr(jax, "shard_map"):  # pragma: no cover - old jax
            raise NotImplementedError(
                "attention_impl='paged' over a tp mesh needs jax.shard_map "
                "(jax >= 0.6); use attention_impl='dense'")
        if kh % mesh.shape["tp"]:
            raise ValueError(
                f"n_kv_heads={kh} not divisible by tp={mesh.shape['tp']}")
        P = jax.sharding.PartitionSpec
        heads = P(None, "tp")                 # q [slots, KH, G, D]
        stacked = P(None, None, "tp")         # pool / staging [L, *, KH, ...]
        fn = jax.shard_map(
            _call, mesh=mesh,
            in_specs=(heads, P(), P(), P(), P(), stacked, stacked,
                      stacked, stacked),
            out_specs=heads,
            axis_names=frozenset({"tp"}),
            check_vma=False,
        )
        return fn(q, block_tables, base, sl, layer, k_stage, v_stage,
                  k_pages, v_pages)
    return _call(q, block_tables, base, sl, layer, k_stage, v_stage,
                 k_pages, v_pages)
