"""Paged-attention decode kernel (Pallas TPU).

The framework's native answer to the decode kernel the reference buys
from vLLM (``python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py`` — the engine all of ``ray.llm`` delegates token
generation to). One decode step reads, per sequence, ONLY the KV pages
that hold live context: the sequence's block table is scalar-prefetched
into SMEM, and the kernel's input index maps walk it so the pipelined
HBM→VMEM copies fetch just the live pages, accumulating flash-style
online softmax per page block. HBM traffic per step is
``O(live_tokens)`` per slot — a dense gather pays the capacity (or the
batch-max bucket) for EVERY slot.

Layout contract (matches ``llm/model.py``):

    k_pages / v_pages : [L, num_pages, KH, page_size, D]  (stacked pool;
                        a single-layer [num_pages, ...] pool is promoted)
    block_tables      : [slots, max_pages_per_seq] int32
    pos               : [slots] int32 — attend over [0, pos] inclusive
    q                 : [slots, KH, G, D]  (G = q heads per kv head)

Kernel structure:
  * grid = (slots, page_blocks), trailing axis sequential on-core so
    the f32 online-softmax state (m / l / acc scratch) carries across
    the page blocks of one sequence.
  * A grid step covers ``ppb`` pages (~256 tokens). Discontiguous pages
    can't ride one BlockSpec, so the pool is passed ``ppb`` times, each
    input's index map selecting one page of the block —
    auto-pipelining then double-buffers all of them. (Manual
    ``make_async_copy`` from HBM needs 128-aligned minor dims, which
    head_dim 64 models violate; pipelined copies don't.)
  * Dead blocks — past the live page count — clamp their index maps to
    the last live page. Pallas elides copies whose block index repeats,
    and ``pl.when`` skips the compute, so dead blocks cost neither
    bandwidth nor FLOPs.
  * **The kernel owns the pool's token write.** The pool holds
    positions [0, pos); the CURRENT token's K/V arrive as separate
    small inputs, are folded into the softmax at the final block, and
    are written into the pool through aliased outputs
    (``input_output_aliases``) at (layer, write_idx, :, pos % page).
    This is what keeps the donated pool IN PLACE across the layer scan:
    any pool-mutating op outside the opaque custom call (a plain XLA
    scatter before or after it) makes XLA materialize a pool-sized copy
    per step — measured ~60 ms/step on a 1B model's 2 GB pool.
  * GQA without K/V replication: per kv head, q is [G, D] against the
    head's [T, D] page block (static loop over KH — decode is
    bandwidth-bound; MXU utilization is irrelevant here).

Off-TPU the kernel runs in interpreter mode (tests); the engine keeps
the dense path as the CPU default since interpret-mode decode is slow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    bt_ref,      # [slots, max_pages] int32 (SMEM, scalar-prefetched)
    pos_ref,     # [slots] int32 (SMEM)
    l_ref,       # [1] int32 layer index (SMEM; consumed by index maps)
    wp_ref,      # [slots] int32 write page (trash-redirected; index maps)
    q_ref,       # [1, KH, Gp, D] VMEM block
    kc_ref,      # [1, KH, 1, D] current token's K (not yet in the pool)
    vc_ref,      # [1, KH, 1, D] current token's V
    *refs,       # [wpk, wpv (write-back only),] ppb k-page refs, ppb
                 # v-page refs ([1, 1, KH, page, D]), then outputs
                 # (o [, k_pool, v_pool]), then scratch m/l/acc
    kh: int,
    page_size: int,
    ppb: int,
    n_blocks: int,
    scale: float,
    write_back: bool,
):
    if write_back:
        wpk_ref, wpv_ref = refs[:2]
        refs = refs[2:]
    k_refs = refs[:ppb]
    v_refs = refs[ppb:2 * ppb]
    if write_back:
        o_ref, kp_out, vp_out, m_ref, lsum_ref, acc_ref = refs[2 * ppb:]
    else:
        o_ref, m_ref, lsum_ref, acc_ref = refs[2 * ppb:]
    si = pl.program_id(0)
    bi = pl.program_id(1)
    pos = pos_ref[si]
    # The pool holds positions [0, pos) — the CURRENT token's K/V arrive
    # through kc/vc instead and are written back below.
    n_live_pages = jax.lax.div(pos + page_size - 1, page_size)
    needed = bi * ppb < n_live_pages

    @pl.when(bi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        lsum_ref[...] = jnp.zeros_like(lsum_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if write_back:
        # Token write as full-page read-modify-write through the aliased
        # pool outputs (a 1-row output block violates TPU tiling): copy
        # the write page, select-replace the token's row, flush. Pallas
        # flushes when the output index (slot) changes — page ownership
        # is exclusive per slot, so no cross-slot hazard.
        off = jax.lax.rem(pos, page_size)
        row = jax.lax.broadcasted_iota(
            jnp.int32, (kh, page_size, q_ref.shape[3]), 1) == off
        kp_out[0, 0] = jax.lax.select(
            row, jnp.broadcast_to(kc_ref[0, :, 0][:, None], row.shape
                                  ).astype(kp_out.dtype), wpk_ref[0, 0])
        vp_out[0, 0] = jax.lax.select(
            row, jnp.broadcast_to(vc_ref[0, :, 0][:, None], row.shape
                                  ).astype(vp_out.dtype), wpv_ref[0, 0])

    @pl.when(needed)
    def _compute():
        t = ppb * page_size
        gp = q_ref.shape[2]
        # Token liveness within the block: global position < pos (strict
        # — position pos itself is the in-flight token, folded below).
        t_pos = bi * t + jax.lax.broadcasted_iota(jnp.int32, (gp, t), 1)
        live = t_pos < pos

        for h in range(kh):
            q = q_ref[0, h]                                   # [Gp, D]
            kb = jnp.concatenate([r[0, 0, h] for r in k_refs])  # [T, D]
            vb = jnp.concatenate([r[0, 0, h] for r in v_refs])
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [Gp, T]
            # lax.select, not jnp.where: jnp's scalar-broadcast wrapper
            # lowers to a closed_call that trips a lowering-cache
            # KeyError (jax 0.9.0) when this kernel sits in an outer scan.
            s = jax.lax.select(live, s, jnp.full_like(s, NEG_INF))
            m_prev = m_ref[h]                                 # [Gp, 128]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
            p = jnp.exp(s - m_new[:, :1])
            alpha = jnp.exp(m_prev - m_new)
            lsum_ref[h] = lsum_ref[h] * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=1, keepdims=True), lsum_ref[h].shape)
            acc_ref[h] = acc_ref[h] * alpha[:, :1] + jax.lax.dot(
                p.astype(vb.dtype), vb, preferred_element_type=jnp.float32)
            m_ref[h] = m_new

    @pl.when(bi == n_blocks - 1)
    def _final():
        # Fold in the current token (always attended: position == pos),
        # then normalize. Also covers pos == 0, where no pool block ran
        # (m = -inf, l = 0) and the output is exactly v_cur.
        for h in range(kh):
            q = q_ref[0, h]                                   # [Gp, D]
            kc = kc_ref[0, h]                                 # [1, D]
            vc = vc_ref[0, h]
            # Elementwise multiply-reduce, not an Nx1 dot: Mosaic's
            # lowering of a [Gp, D] x [1, D] matmul with bf16 operands
            # and f32 accumulation emits a type-mismatched broadcast.
            s = jnp.sum(
                q.astype(jnp.float32) * kc.astype(jnp.float32),
                axis=1, keepdims=True,
            ) * scale                                         # [Gp, 1]
            m_prev = m_ref[h]
            m_new = jnp.maximum(m_prev, jnp.broadcast_to(s, m_prev.shape))
            p = jnp.exp(s - m_new[:, :1])                     # [Gp, 1]
            alpha = jnp.exp(m_prev - m_new)
            lsum = lsum_ref[h] * alpha + jnp.broadcast_to(p, lsum_ref[h].shape)
            acc = acc_ref[h] * alpha[:, :1] + p * vc.astype(jnp.float32)
            o_ref[0, h] = (acc / lsum[:, :1]).astype(o_ref.dtype)


# NOTE: deliberately NOT @jax.jit-wrapped — a nested jit around a
# pallas_call inside an outer scan trips a lowering-cache KeyError in
# jax 0.9.0 ('closed_call' in cached_primitive_lowerings). Callers are
# always under jit themselves (decode_loop / decode_step).
def paged_decode_attention(
    q,
    k_pages,
    v_pages,
    block_tables,
    pos,
    k_cur=None,
    v_cur=None,
    *,
    page_size: int,
    pages_per_block: int | None = None,
    live_pages: int | None = None,
    layer=None,
    write_idx=None,
    interpret: bool | None = None,
):
    """One decode step of attention over a paged KV pool.

    q:            [slots, KH, G, D] — current-token queries, grouped by
                  kv head (``q.reshape(slots, KH, G, D)`` of the [H, D]
                  layout, matching ``llm/model.py``'s GQA grouping).
    k/v_pages:    [num_pages, KH, page_size, D] — one layer's pool — or
                  the FULL stacked pool [L, num_pages, KH, page_size, D]
                  with ``layer`` the (traced) layer index. Passing the
                  stacked pool lets the layer scan keep the pool in its
                  carry: the layer index rides the scalar prefetch into
                  the page index maps, so no [num_pages, ...] slice is
                  ever materialized.
    k_cur/v_cur:  [slots, KH, D] — the CURRENT token's K/V, folded into
                  the softmax at the final block. The pool must hold
                  positions [0, pos) only. If omitted, the pool must
                  instead already hold position ``pos`` (read-only mode;
                  the wrapper pulls the token back out of the pool).
    write_idx:    [slots] int32 — page each slot's token is written to
                  (the caller's trash-redirected page). When given (with
                  k_cur/v_cur), the kernel WRITES the token into the
                  pool through aliased outputs and returns
                  ``(out, k_pages, v_pages)``; the caller must not
                  scatter separately. This in-kernel write is what keeps
                  a donated, loop-carried pool in place — any XLA-side
                  scatter next to the opaque custom call forces a
                  pool-sized copy per step.
    block_tables: [slots, max_pages_per_seq] int32.
    pos:          [slots] int32 — attend over [0, pos] inclusive.
    live_pages:   static upper bound on live pages of ANY slot (i.e.
                  ``max(pos) // page_size + 1`` ≤ live_pages). Bounds the
                  GRID, not just the copies: without it, dead blocks
                  still pay per-step pipeline bookkeeping, so step count
                  scales with pool capacity. Callers should bucket it
                  (powers of two) to bound recompiles.

    Returns [slots, KH, G, D] in q.dtype — plus the updated pool arrays
    when ``write_idx`` is given.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    squeeze_layer = k_pages.ndim == 4
    if squeeze_layer:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    layer = (jnp.zeros((1,), jnp.int32) if layer is None
             else jnp.asarray(layer, jnp.int32).reshape(1))
    n, kh, g, d = q.shape
    max_pages = block_tables.shape[1]
    write_back = write_idx is not None
    if k_cur is None:
        if write_back:
            raise ValueError("write_idx requires k_cur/v_cur")
        # Pool already holds position ``pos``: pull the current token's
        # K/V back out so the kernel's strict (< pos) pool mask plus the
        # explicit current-token fold gives identical semantics.
        wp = jnp.take_along_axis(
            block_tables,
            jnp.minimum(pos // page_size, max_pages - 1)[:, None], axis=1)[:, 0]
        off = pos % page_size
        k_cur = k_pages[layer[0], wp, :, off]              # [slots, KH, D]
        v_cur = v_pages[layer[0], wp, :, off]
    if write_idx is None:
        write_idx = jnp.zeros((n,), jnp.int32)             # unused
    covered = max_pages if live_pages is None else min(live_pages, max_pages)
    # ~256 tokens of context per grid step: few enough steps that grid
    # overhead stays small, few enough inputs that VMEM stays bounded.
    if pages_per_block is None:
        pages_per_block = max(1, min(covered, 256 // page_size, 8))
    ppb = min(pages_per_block, covered)
    n_blocks = -(-covered // ppb)

    # Pad G to the f32 sublane tile (8) so scratch/compute rows are
    # aligned; padded q rows are zeros and their outputs are sliced off.
    gp = -(-g // 8) * 8
    if gp != g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    def page_index_map(j):
        # Page j of block bi for slot si; dead/overflow indices clamp to
        # the last live page so consecutive steps repeat the block index
        # and Pallas skips the copy. (Scalar-prefetch refs arrive as
        # trailing index-map args; lax ops, not jnp — see closed_call
        # note above.)
        def index_map(si, bi, bt_ref, pos_ref, l_ref, wp_ref):
            n_live = jax.lax.div(pos_ref[si] + page_size - 1, page_size)
            logical = jax.lax.max(
                jax.lax.min(bi * ppb + j,
                            jax.lax.min(n_live, max_pages) - 1), 0)
            return l_ref[0], bt_ref[si, logical], 0, 0, 0
        return index_map

    def wpage_map(si, bi, bt_ref, pos_ref, l_ref, wp_ref):
        return l_ref[0], wp_ref[si], 0, 0, 0

    page_block = (1, 1, kh, page_size, d)
    kernel = functools.partial(
        _decode_kernel,
        kh=kh,
        page_size=page_size,
        ppb=ppb,
        n_blocks=n_blocks,
        scale=d ** -0.5,
        write_back=write_back,
    )
    out_specs = [pl.BlockSpec((1, kh, gp, d), lambda si, bi, *_: (si, 0, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((n, kh, gp, d), q.dtype)]
    aliases = {}
    wpage_inputs = []
    wpage_specs = []
    if write_back:
        out_specs += [pl.BlockSpec(page_block, wpage_map)] * 2
        out_shape += [jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                      jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)]
        wpage_inputs = [k_pages, v_pages]
        wpage_specs = [pl.BlockSpec(page_block, wpage_map)] * 2
        # Flattened operand order: bt, pos, layer, wp, q, kc, vc, wpk,
        # wpv, k_pages x ppb, v_pages x ppb. Alias the first ref of each
        # pool to its output so the buffer passes through un-copied.
        aliases = {9: 1, 9 + ppb: 2}
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n, n_blocks),
        in_specs=[
            pl.BlockSpec((1, kh, gp, d), lambda si, bi, *_: (si, 0, 0, 0)),
            pl.BlockSpec((1, kh, 1, d), lambda si, bi, *_: (si, 0, 0, 0)),
            pl.BlockSpec((1, kh, 1, d), lambda si, bi, *_: (si, 0, 0, 0)),
            *wpage_specs,
            *[pl.BlockSpec(page_block, page_index_map(j)) for j in range(ppb)],
            *[pl.BlockSpec(page_block, page_index_map(j)) for j in range(ppb)],
        ],
        out_specs=out_specs if write_back else out_specs[0],
        scratch_shapes=[
            pltpu.VMEM((kh, gp, 128), jnp.float32),
            pltpu.VMEM((kh, gp, 128), jnp.float32),
            pltpu.VMEM((kh, gp, d), jnp.float32),
        ],
    )
    result = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape if write_back else out_shape[0],
        input_output_aliases=aliases,
        interpret=interpret,
    )(block_tables, pos, layer, write_idx,
      q, k_cur[:, :, None], v_cur[:, :, None], *wpage_inputs,
      *([k_pages] * ppb), *([v_pages] * ppb))
    if write_back:
        out, new_k, new_v = result
        out = out[:, :, :g] if gp != g else out
        if squeeze_layer:
            new_k, new_v = new_k[0], new_v[0]
        return out, new_k, new_v
    out = result
    return out[:, :, :g] if gp != g else out
