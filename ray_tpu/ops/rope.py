"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hook."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, *, theta: float = 500_000.0):
    """Inverse frequencies for each (even) head-dim channel pair."""
    return 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )


def apply_rope(x, positions, *, theta: float = 500_000.0):
    """Rotate q or k. x: [B, H, S, D]; positions: [B, S] or [S] int32.

    Uses the split-halves convention (rotate_half), matching Llama.
    Computed in f32, cast back to the input dtype.
    """
    b, h, s, d = x.shape
    inv_freq = rope_frequencies(d, theta=theta)
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[:, None, :, None].astype(jnp.float32) * inv_freq  # [B,1,S,D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
