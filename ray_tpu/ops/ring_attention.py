"""Ring attention: exact attention over a sequence sharded across devices.

Green-field — the reference has no sequence/context parallelism at all
(SURVEY.md §5.7); long context is delegated to vLLM. Here it is a
first-class op: each device holds a contiguous sequence chunk of Q/K/V;
KV chunks rotate around the ``sp`` ring via ``lax.ppermute`` while each
device folds every chunk into an online-softmax accumulator. Compute on
chunk t overlaps the transfer of chunk t+1 (XLA schedules the ppermute
DMA concurrently with the einsums on ICI).

Call inside ``shard_map`` with the sequence axis mapped to ``sp``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def ring_attention(
    q,
    k,
    v,
    *,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: float | None = None,
):
    """q [B,Hq,Sl,D], k/v [B,Hkv,Sl,D] — Sl is the per-device chunk; devices
    hold chunks in ring order. Returns the local output chunk [B,Hq,Sl,D].
    """
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, hq, sl, d = q.shape
    hkv = k.shape[1]
    g = hq // hkv
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    # GQA via a grouped head axis: K/V stay at hkv heads, so each ring hop
    # ships 1/g of the bytes a repeat-to-hq layout would
    qf = (q.astype(jnp.float32) * scale).reshape(b, hkv, g, sl, d)

    q_pos = my * sl + jnp.arange(sl)  # global positions of local q rows

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(t, kc, vc, acc, m, l):
        src = (my - t) % n  # which global chunk this kv block is
        s = jnp.einsum(
            "bhgqd,bhkd->bhgqk", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = src * sl + jnp.arange(sl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        # Explicitly zero masked entries: for a fully-masked row m_new is
        # still NEG_INF and exp(s - m_new) would be exp(0) = 1, so the
        # mask (not underflow) must kill those probabilities. Correct for
        # any rotation schedule, not just diagonal-first.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    def step(t, carry):
        kc, vc, acc, m, l = carry
        acc, m, l = attend(t, kc, vc, acc, m, l)
        kc = lax.ppermute(kc, axis, perm=perm)
        vc = lax.ppermute(vc, axis, perm=perm)
        return kc, vc, acc, m, l

    acc0 = jnp.zeros((b, hkv, g, sl, d), jnp.float32)
    m0 = jnp.full((b, hkv, g, sl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, sl), jnp.float32)
    # rotate n-1 times; the final chunk attends without a dead last ppermute
    kc, vc, acc, m, l = lax.fori_loop(0, n - 1, step, (k, v, acc0, m0, l0))
    acc, m, l = attend(n - 1, kc, vc, acc, m, l)
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.reshape(b, hq, sl, d).astype(q.dtype)
