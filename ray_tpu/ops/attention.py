"""Flash attention as a Pallas TPU kernel.

Online-softmax tiled attention (Dao et al.) laid out for the MXU: the grid
iterates (batch, head, q_block, k_block) with the k_block axis innermost —
TPU grids execute the trailing axis sequentially on-core, so f32
accumulators live in VMEM scratch across k steps. Inputs stay bf16 for the
MXU; softmax statistics and the output accumulator are f32.

The reference has no attention kernel of its own (it delegates all model
compute to torch/vLLM); this is the TPU-native equivalent of the kernels
those stacks supply.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def mha_reference(q, k, v, *, causal: bool = True, sm_scale: float | None = None):
    """Pure-jnp attention; ground truth for kernel tests and the CPU path.

    Shapes: q [B, Hq, S, D], k/v [B, Hkv, S, D]; GQA when Hq > Hkv.
    """
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=1)
        v = jnp.repeat(v, hq // hkv, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        sk = k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(probs.dtype)).astype(q.dtype)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale, causal, block_q, block_k, n_k
):
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: skip blocks strictly above the diagonal
    needed = jnp.logical_or(
        jnp.logical_not(causal), k_start <= q_start + block_q - 1
    )

    @pl.when(needed)
    def _compute():
        # Keep q/k/v in bf16 for the MXU (f32 inputs would run the MXU at a
        # fraction of peak); accumulate in f32 via preferred_element_type.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1] -> broadcast over lanes
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        p = jnp.exp(s - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_ref.shape
        )
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(ki == n_k - 1)
    def _final():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _flash_forward(
    q,
    k,
    v,
    *,
    causal: bool,
    sm_scale: float | None,
    block_q: int,
    block_k: int,
    interpret: bool,
):
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    rep = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # fallback for shapes the TPU tiling can't take: ragged blocks or blocks
    # not multiple of the bf16 sublane tile (16)
    if sq % block_q or sk % block_k or block_q % 16 or block_k % 16:
        return mha_reference(q, k, v, causal=causal, sm_scale=scale)
    n_q, n_k = sq // block_q, sk // block_k

    grid = (b, hq, n_q, n_k)
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        n_k=n_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # GQA: map query head to its kv head in the index_map — no
            # repeated K/V materialization in HBM
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // rep, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _mha_backward_blocked(q, k, v, g, *, causal, sm_scale, block_q):
    """Flash-style blocked attention backward in plain JAX.

    Scans over q chunks, recomputing softmax per chunk — peak extra memory
    is O(block_q × S) per step instead of O(S²), which is what lets a
    1B-param model train at 8×2048 tokens on one 16 GB v5e chip.
    All heads already expanded (GQA handled by caller).
    """
    b, h, s, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, s)
    if s % block_q:
        block_q = s  # unblocked fallback for ragged sizes
    nq = s // block_q
    k_pos = jnp.arange(s)

    def body(carry, xs):
        dk_acc, dv_acc = carry
        q_blk, g_blk, q0 = xs  # [B,H,bq,D], [B,H,bq,D], scalar block start
        # bf16 operands on every dot (f32 inputs would cripple the MXU);
        # f32 accumulation via preferred_element_type.
        sblk = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k,
                          preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q0 + jnp.arange(block_q)
            mask = q_pos[:, None] >= k_pos[None, :]
            sblk = jnp.where(mask[None, None], sblk, NEG_INF)
        p = jax.nn.softmax(sblk, axis=-1)
        pb = p.astype(q.dtype)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g_blk, v,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.sum(p * dp, axis=-1, keepdims=True))).astype(q.dtype)
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, k,
                            preferred_element_type=jnp.float32) * scale
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, q_blk,
                                     preferred_element_type=jnp.float32) * scale
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", pb, g_blk,
                                     preferred_element_type=jnp.float32)
        return (dk_acc, dv_acc), dq_blk

    q_blocks = q.reshape(b, h, nq, block_q, d).transpose(2, 0, 1, 3, 4)
    g_blocks = g.reshape(b, h, nq, block_q, d).transpose(2, 0, 1, 3, 4)
    starts = jnp.arange(nq) * block_q
    (dk, dv), dq_blocks = jax.lax.scan(
        body,
        (jnp.zeros((b, h, s, d), jnp.float32), jnp.zeros((b, h, s, d), jnp.float32)),
        (q_blocks, g_blocks, starts),
    )
    dq = dq_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.lru_cache(maxsize=None)
def _make_flash(causal, sm_scale, block_q, block_k, interpret):
    """custom_vjp wrapper: Pallas kernel forward, blocked-recompute backward."""

    @jax.custom_vjp
    def f(q, k, v):
        return _flash_forward(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        hq, hkv = q.shape[1], k.shape[1]
        if hq != hkv:
            rep = hq // hkv
            k_full = jnp.repeat(k, rep, axis=1)
            v_full = jnp.repeat(v, rep, axis=1)
        else:
            k_full, v_full = k, v
        dq, dk, dv = _mha_backward_blocked(
            q, k_full, v_full, g, causal=causal, sm_scale=sm_scale, block_q=block_q
        )
        if hq != hkv:
            b, _, s, d = dk.shape
            dk = dk.reshape(b, hkv, rep, s, d).sum(axis=2)
            dv = dv.reshape(b, hkv, rep, s, d).sum(axis=2)
        return dq, dk, dv

    f.defvjp(fwd, bwd)
    return f


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
):
    """Tiled attention. q [B,Hq,S,D], k/v [B,Hkv,S,D] (GQA folded by repeat).

    Differentiable (custom VJP); falls back to the interpreter off-TPU so
    tests run on the CPU mesh.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _make_flash(causal, sm_scale, block_q, block_k, interpret)(q, k, v)
