"""Flash attention as a Pallas TPU kernel.

Online-softmax tiled attention (Dao et al.) laid out for the MXU: the grid
iterates (batch, head, q_block, k_block) with the k_block axis innermost —
TPU grids execute the trailing axis sequentially on-core, so f32
accumulators live in VMEM scratch across k steps. Inputs stay bf16 for the
MXU; softmax statistics and the output accumulator are f32.

The reference has no attention kernel of its own (it delegates all model
compute to torch/vLLM); this is the TPU-native equivalent of the kernels
those stacks supply.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def mha_reference(q, k, v, *, causal: bool = True, sm_scale: float | None = None):
    """Pure-jnp attention; ground truth for kernel tests and the CPU path.

    Shapes: q [B, Hq, S, D], k/v [B, Hkv, S, D]; GQA when Hq > Hkv.
    """
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=1)
        v = jnp.repeat(v, hq // hkv, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        sk = k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(probs.dtype)).astype(q.dtype)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, sm_scale, causal, block_q, block_k, n_k
):
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: skip blocks strictly above the diagonal
    needed = jnp.logical_or(
        jnp.logical_not(causal), k_start <= q_start + block_q - 1
    )

    @pl.when(needed)
    def _compute():
        # Keep q/k/v in bf16 for the MXU (f32 inputs would run the MXU at a
        # fraction of peak); accumulate in f32 via preferred_element_type.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1] -> broadcast over lanes
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        p = jnp.exp(s - m_new[:, :1])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_ref.shape
        )
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(ki == n_k - 1)
    def _final():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp residual for the backward kernels, replicated
            # along lanes (the jax TPU flash layout: [B,H,S,128]).
            lse_ref[0, 0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _fit_block(requested: int, seq: int) -> int:
    """Largest block <= requested that divides ``seq`` and is a multiple
    of the bf16 sublane tile (16) — so e.g. S=1536 stays on the Pallas
    kernel with 512-wide blocks instead of silently falling back to the
    unblocked reference when the default block does not divide it."""
    b = min(requested, seq)
    while b >= 16 and (seq % b or b % 16):
        b -= 16
    return max(b, 16)


def _flash_forward(
    q,
    k,
    v,
    *,
    causal: bool,
    sm_scale: float | None,
    block_q: int,
    block_k: int,
    interpret: bool,
    save_residuals: bool = False,
):
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    rep = hq // hkv
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    # fallback for shapes the TPU tiling can't take: ragged blocks or blocks
    # not multiple of the bf16 sublane tile (16)
    if sq % block_q or sk % block_k or block_q % 16 or block_k % 16:
        o = mha_reference(q, k, v, causal=causal, sm_scale=scale)
        return (o, None) if save_residuals else o
    n_q, n_k = sq // block_q, sk // block_k

    grid = (b, hq, n_q, n_k)
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        n_k=n_k,
    )
    if not save_residuals:
        def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                   _inner=kernel):
            _inner(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref)

    out_specs = [pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if save_residuals:
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, hq, sq, 128), jnp.float32))
    result = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # GQA: map query head to its kv head in the index_map — no
            # repeated K/V materialization in HBM
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // rep, ki, 0)),
        ],
        out_specs=out_specs if save_residuals else out_specs[0],
        out_shape=out_shape if save_residuals else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return result


def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, sm_scale, causal, block_q, block_k, n_k):
    """dQ: for one q block, accumulate ds @ K over all k blocks (k axis
    innermost → sequential on-core, acc lives in VMEM)."""
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = jnp.logical_or(jnp.logical_not(causal), k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        g = g_ref[0, 0]
        lse = lse_ref[0, 0]      # [bq, 128] lanes-replicated
        delta = delta_ref[0, 0]  # [bq, 128]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])  # masked entries underflow to 0
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(q.dtype)
        acc_ref[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _final():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc,
                     *, sm_scale, causal, block_q, block_k, n_q):
    """dK/dV: for one k block, accumulate over all q blocks (q axis
    innermost). p/ds are computed q-major and contracted over the q dim
    (dot_general) — no transposes materialize."""
    qi = pl.program_id(3)
    ki = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = jnp.logical_or(jnp.logical_not(causal), q_start + block_q - 1 >= k_start)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        g = g_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        p = jnp.exp(s - lse[:, :1])                       # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [bk, d]
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, :1]) * sm_scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                                  # [bk, d]

    @pl.when(qi == n_q - 1)
    def _final():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, *, causal, sm_scale, block_q, block_k,
                    interpret):
    """Pallas dq/dk/dv. K/V stay at kv-head count (GQA via index maps);
    dk/dv come out at q-head count and are reduced by the caller."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    rep = hq // hkv
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    n_q, n_k = sq // block_q, sk // block_k
    # delta = rowsum(dO * O), lanes-replicated like lse.
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                keepdims=True),
        (b, hq, sq, 128),
    )

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi // rep, ki, 0))
    lm_spec = pl.BlockSpec((1, 1, block_q, 128), lambda bi, hi, ki, qi: (bi, hi, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_k=n_k),
        grid=(b, hq, n_q, n_k),  # k innermost
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, sm_scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q),
        grid=(b, hq, n_k, n_q),  # q innermost
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lm_spec, lm_spec],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hq, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    if rep > 1:
        dk = dk.reshape(b, hkv, rep, sk, d).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(b, hkv, rep, sk, d).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


def _mha_backward_blocked(q, k, v, g, *, causal, sm_scale, block_q):
    """Flash-style blocked attention backward in plain JAX.

    Scans over q chunks, recomputing softmax per chunk — peak extra memory
    is O(block_q × S) per step instead of O(S²), which is what lets a
    1B-param model train at 8×2048 tokens on one 16 GB v5e chip.
    All heads already expanded (GQA handled by caller).
    """
    b, h, s, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_q = _fit_block(block_q, s)
    if s % block_q:
        block_q = s  # unblocked fallback for ragged sizes
    nq = s // block_q
    k_pos = jnp.arange(s)

    def body(carry, xs):
        dk_acc, dv_acc = carry
        q_blk, g_blk, q0 = xs  # [B,H,bq,D], [B,H,bq,D], scalar block start
        # bf16 operands on every dot (f32 inputs would cripple the MXU);
        # f32 accumulation via preferred_element_type.
        sblk = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k,
                          preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q0 + jnp.arange(block_q)
            mask = q_pos[:, None] >= k_pos[None, :]
            sblk = jnp.where(mask[None, None], sblk, NEG_INF)
        p = jax.nn.softmax(sblk, axis=-1)
        pb = p.astype(q.dtype)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g_blk, v,
                        preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.sum(p * dp, axis=-1, keepdims=True))).astype(q.dtype)
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, k,
                            preferred_element_type=jnp.float32) * scale
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, q_blk,
                                     preferred_element_type=jnp.float32) * scale
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", pb, g_blk,
                                     preferred_element_type=jnp.float32)
        return (dk_acc, dv_acc), dq_blk

    q_blocks = q.reshape(b, h, nq, block_q, d).transpose(2, 0, 1, 3, 4)
    g_blocks = g.reshape(b, h, nq, block_q, d).transpose(2, 0, 1, 3, 4)
    starts = jnp.arange(nq) * block_q
    (dk, dv), dq_blocks = jax.lax.scan(
        body,
        (jnp.zeros((b, h, s, d), jnp.float32), jnp.zeros((b, h, s, d), jnp.float32)),
        (q_blocks, g_blocks, starts),
    )
    dq = dq_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _blocks_fit(sq, sk, block_q, block_k) -> bool:
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    return not (sq % block_q or sk % block_k or block_q % 16 or block_k % 16)


@functools.lru_cache(maxsize=None)
def _make_flash(causal, sm_scale, block_q, block_k, interpret):
    """custom_vjp wrapper: Pallas kernels for BOTH directions (forward
    saves the logsumexp residual; dq and dk/dv are dedicated kernels).
    Ragged shapes fall back to the jnp blocked paths."""

    @jax.custom_vjp
    def f(q, k, v):
        return _flash_forward(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )

    def fwd(q, k, v):
        if not _blocks_fit(q.shape[2], k.shape[2], block_q, block_k):
            return f(q, k, v), (q, k, v, None, None)
        o, lse = _flash_forward(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
            save_residuals=True,
        )
        return o, (q, k, v, o, lse)

    def bwd(res, g):
        q, k, v, o, lse = res
        if lse is not None:
            return _flash_backward(
                q, k, v, o, lse, g, causal=causal, sm_scale=sm_scale,
                block_q=block_q, block_k=block_k, interpret=interpret,
            )
        # Ragged fallback: blocked-recompute backward in plain JAX.
        hq, hkv = q.shape[1], k.shape[1]
        if hq != hkv:
            rep = hq // hkv
            k_full = jnp.repeat(k, rep, axis=1)
            v_full = jnp.repeat(v, rep, axis=1)
        else:
            k_full, v_full = k, v
        dq, dk, dv = _mha_backward_blocked(
            q, k_full, v_full, g, causal=causal, sm_scale=sm_scale, block_q=block_q
        )
        if hq != hkv:
            b, _, s, d = dk.shape
            dk = dk.reshape(b, hkv, rep, s, d).sum(axis=2)
            dv = dv.reshape(b, hkv, rep, s, d).sum(axis=2)
        return dq, dk, dv

    f.defvjp(fwd, bwd)
    return f


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
):
    """Tiled attention. q [B,Hq,S,D], k/v [B,Hkv,S,D] (GQA folded by repeat).

    Differentiable (custom VJP); falls back to the interpreter off-TPU so
    tests run on the CPU mesh. Default 1024x1024 blocks: measured on v5e
    at head_dim 64 they run the fwd+bwd ~14% faster at seq 2k and ~46%
    faster at seq 32k than 512x512 (fewer per-block VPU rescales); 2048
    blocks exceed the 16 MiB scoped-VMEM stack limit.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _make_flash(causal, sm_scale, block_q, block_k, interpret)(q, k, v)
