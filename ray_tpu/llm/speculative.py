"""Speculative decoding: host-side drafting for the verify dispatch.

Decode's steady state costs one target-model dispatch per token.
Speculative decoding (Leviathan et al. 2023; Chen et al. 2023) amortizes
that: a cheap **drafter** proposes K tokens per active slot, then ONE
target-model dispatch (``model.verify_block``) scores all K+1 positions
per slot in parallel — exactly a tiny prefill chunk — and accepts the
longest prefix of the draft the target agrees with. Output is lossless:
greedy acceptance is exact argmax equality (byte parity with plain
decode), and at temperature > 0 the standard rejection-sampling rule
preserves the target distribution exactly.

The drafter here is deliberately model-free: **n-gram / prompt-lookup
self-drafting** (the "prompt lookup decoding" trick) — the continuation
of the longest recent n-gram that already occurred earlier in the
sequence is proposed verbatim. No extra weights, no extra dispatch, and
it wins big on retrieval/multi-turn/code traffic where the output quotes
its own context. A small draft model slots in later by implementing
``Drafter`` (its ``draft`` just runs the cheap model host- or
device-side); the engine only ever sees the interface.
"""

from __future__ import annotations

from dataclasses import dataclass


class Drafter:
    """Proposes up to ``k`` continuation tokens for one sequence.

    ``tokens`` is the full token history (prompt + generated so far);
    the proposal is a guess at the NEXT ``k`` tokens. Returning fewer
    than ``k`` (or ``[]``) is always safe — the verify dispatch treats
    missing positions as auto-rejected padding, and a step with no
    drafts at all falls back to the plain fused decode burst."""

    def draft(self, tokens: list[int], k: int) -> list[int]:
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup self-drafter: match the sequence's trailing n-gram
    (longest first, ``ngram_max`` down to ``ngram_min``) against its own
    earlier tokens and propose the continuation of the MOST RECENT
    earlier occurrence. Zero model cost; accuracy comes entirely from
    repetition in the traffic (multi-turn resends, retrieval quotes,
    structured output)."""

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1):
        self.ngram_max = max(1, ngram_max)
        self.ngram_min = max(1, min(ngram_min, self.ngram_max))

    def draft(self, tokens: list[int], k: int) -> list[int]:
        n_tok = len(tokens)
        if k <= 0 or n_tok < self.ngram_min + 1:
            return []
        for n in range(min(self.ngram_max, n_tok - 1), self.ngram_min - 1, -1):
            pattern = tokens[n_tok - n:]
            # Most recent earlier occurrence whose continuation exists:
            # scan right-to-left over starts j with j + n < n_tok.
            for j in range(n_tok - n - 1, -1, -1):
                if tokens[j:j + n] == pattern:
                    return list(tokens[j + n:j + n + k])
        return []


@dataclass
class SpeculationConfig:
    """Engine/serving knobs for speculative decoding.

    num_draft_tokens: K — drafted tokens verified per dispatch (the
        verify program scores K+1 positions; emitted tokens per dispatch
        range 1..K+1, so acceptance 0 still advances one token).
    drafter: ``"ngram"`` (the built-in self-drafter) or a ``Drafter``
        instance (e.g. a small draft model wrapper).
    ngram_max/ngram_min: n-gram lengths the lookup tries, longest first.
    """

    num_draft_tokens: int = 4
    drafter: object = "ngram"
    ngram_max: int = 3
    ngram_min: int = 1

    def __post_init__(self):
        self.num_draft_tokens = max(1, int(self.num_draft_tokens))

    @classmethod
    def normalize(cls, value) -> "SpeculationConfig | None":
        """None | dict | SpeculationConfig -> SpeculationConfig | None
        (the shape the serving layer threads through
        ``build_llm_app(speculation_config=...)``)."""
        if value is None:
            return None
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(
            f"speculation_config must be None, a dict, or a "
            f"SpeculationConfig, got {type(value).__name__}")

    def build_drafter(self) -> Drafter:
        if isinstance(self.drafter, Drafter):
            return self.drafter
        if self.drafter == "ngram":
            return NgramDrafter(self.ngram_max, self.ngram_min)
        raise ValueError(f"unknown drafter {self.drafter!r} "
                         "(use 'ngram' or a Drafter instance)")
