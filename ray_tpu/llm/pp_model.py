"""Pipeline-parallel paged-KV inference: layers staged over the ``pp`` axis.

The reference places TP×PP vLLM engines across nodes via placement-group
bundles (``python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:117-168``) and lets vLLM move activations between PP ranks
with NCCL send/recv. TPU redesign: the stacked layer axis of the params
AND of the KV page pool is sharded over the mesh's ``pp`` axis; inside
``shard_map`` each stage scans its LOCAL layers and the rotating
activation moves stage→stage over ICI via ``lax.ppermute``. One jitted
program runs on every stage (SPMD) — no per-rank send/recv choreography.

Schedules:
  * **Decode** fills the pipeline with SLOT GROUPS: the ``slots`` batch is
    split into ``pp`` groups, and at tick ``t`` stage ``s`` runs group
    ``(t - s) mod pp``. A group completes one full decode step per ``pp``
    ticks, so once warm every stage is busy every tick — aggregate decode
    throughput matches the unpipelined engine while params+pages memory
    is 1/pp per device. The freshly sampled token rides the same
    ``ppermute`` ring from the last stage back to stage 0.
  * **Prefill** passes one chunk through the stages sequentially (tick
    ``t`` activates stage ``t``). This wastes (pp-1)/pp of prefill
    compute vs a sequence-pipelined schedule — acceptable because decode
    dominates serving cost; a chunk-pipelined prefill is the natural
    upgrade and slots into the same tick loop.

Group bookkeeping (pos / done / remaining) travels WITH the rotating
activation, so every stage sees the group's current round state without
host synchronization, and finished slots redirect their KV writes to
their private trash page exactly as the unpipelined ``decode_loop`` does.

TP composes inside the stages, two ways:

  * **Dense decode / prefill**: the shard_map is manual over ``pp`` only
    (``axis_names={"pp"}``) — ``tp`` remains an auto axis XLA partitions
    from the params'/pool's shardings, inserting the ICI collectives per
    stage.
  * **Paged decode** (round 15): the Pallas kernel is an opaque custom
    call XLA cannot auto-partition over tp, so nesting it under an
    auto-tp region forced composed pp×tp meshes dense. The fix FLATTENS
    the decode loop to ONE manual region over ``{"pp", "tp"}``: pp stays
    manual on the layer axis (pool + params), tp goes manual on the
    KV-head axis (pool/staging/q — attention is independent per KV
    head, so the kernel runs unchanged on each shard's local heads),
    and the only collectives are the Megatron pair hand-written in
    ``model.decode_block``/``_mlp`` (``tp_axis=``: one ``psum`` after
    the row-parallel ``wo``, one after ``w_down``) plus a tiled
    ``all_gather`` of the per-shard logits before sampling. Greedy
    parity with the unpipelined engine is preserved — the math is the
    same sum, just reduced explicitly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.llama import LlamaConfig
from ..ops import apply_rope, rms_norm
from .model import _gather_ctx, _mlp, _project_qkv, decode_block


def _manual_layer_specs(config: LlamaConfig, axes=("pp", "tp")):
    """Per-leaf PartitionSpecs for ``params["layers"]`` inside a manual
    region over ``axes``: each leaf's logical axes map through the
    standard rule table (layers→pp, heads/kv_heads/mlp→tp), with every
    mesh axis OUTSIDE the manual set dropped (those stay auto/size-1).
    The flattened pp×tp decode region needs real per-leaf specs — a
    blanket ``P("pp")`` would silently all-gather the tp shards."""
    from ..models.llama import param_axes
    from ..parallel.sharding import DEFAULT_RULES

    def to_spec(logical):
        names = []
        for ax in logical:
            mesh_ax = DEFAULT_RULES.get(ax)
            if isinstance(mesh_ax, tuple):
                mesh_ax = next((a for a in mesh_ax if a in axes), None)
            if mesh_ax not in axes:
                mesh_ax = None
            names.append(mesh_ax)
        while names and names[-1] is None:
            names.pop()
        return P(*names)

    return jax.tree.map(to_spec, param_axes(config)["layers"],
                        is_leaf=lambda x: isinstance(x, tuple))


@functools.partial(jax.jit,
                   static_argnames=("config", "page_size", "mesh"),
                   donate_argnames=("pages",))
def pp_prefill_chunk(params, pages, block_table, tokens, start_pos,
                     config: LlamaConfig, page_size: int, mesh,
                     lora=None, lora_slot=None):
    """Pipeline-staged ``prefill_chunk``: same contract as
    ``model.prefill_chunk`` (pages updated, hidden [C, E] returned) with
    params["layers"]/pages sharded P("pp") on the layer axis.
    ``start_pos`` is NOT required to be page-aligned (round 15): the
    chunk's K/V lands via the same row-granular ``(page, offset)``
    scatter the single-host prefill uses, so a prefix-cache partial
    tail-block hit can start the suffix mid-page on a pp mesh too — the
    gate that kept ``supports_prefix_cow`` off the pp path.
    ``lora``/``lora_slot`` apply one adapter to the whole chunk (stacks
    sharded over pp on their layer axis, like the params)."""
    c = config
    pp = mesh.shape["pp"]
    C = tokens.shape[0]
    max_ctx = block_table.shape[0] * page_size
    kh, g = c.n_kv_heads, c.n_heads // c.n_kv_heads
    causal = jnp.arange(C)[:, None] >= jnp.arange(C)[None, :]

    def per_device(layers_local, kp, vp, embed, final_norm,
                   block_table, tokens, start_pos, lora_local=None,
                   lslot=None):
        stage = lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        positions = start_pos + jnp.arange(C, dtype=jnp.int32)
        ctx_live = jnp.arange(max_ctx, dtype=jnp.int32) < start_pos
        # Row-granular write destinations: position p -> (its page, its
        # offset). Pad rows past the table clamp to the last page (masked
        # until decode overwrites them) — identical to model.prefill_chunk.
        write_pages = block_table[jnp.minimum(
            positions // page_size, block_table.shape[0] - 1)]        # [C]
        write_offs = positions % page_size                            # [C]
        x0 = embed[tokens][None].astype(c.dtype)       # [1, C, E]

        def tick(carry, t):
            act, hidden, kp, vp = carry
            live = t == stage                          # this stage holds the chunk
            x = jnp.where((stage == 0) & (t == 0), x0, act)

            def body(carry, xs):
                xc, kp, vp = carry                     # pools [Ll, P, KH, page, D]
                layer, l = xs
                h = rms_norm(xc, layer["attn_norm"], eps=c.norm_eps)
                q, k, v = _project_qkv(h, layer)       # [1, H|KH, C, D]
                if lora_local is not None:
                    from .lora import lora_delta_single

                    def add(t_, p, heads):
                        d = lora_delta_single(
                            h, lora_local[f"{p}.A"], lora_local[f"{p}.B"],
                            l, lslot)
                        return t_ + jnp.swapaxes(
                            d.reshape(1, C, heads, c.head_dim), 1, 2
                        ).astype(t_.dtype)

                    q = add(q, "wq", c.n_heads)
                    k = add(k, "wk", c.n_kv_heads)
                    v = add(v, "wv", c.n_kv_heads)
                q = apply_rope(q, positions, theta=c.rope_theta)
                k = apply_rope(k, positions, theta=c.rope_theta)
                ck = _gather_ctx(kp, l, block_table)   # [KH, ctx, D]
                cv = _gather_ctx(vp, l, block_table)
                qg = q[0].reshape(kh, g, C, c.head_dim)
                scale = c.head_dim ** -0.5
                s_ctx = jnp.einsum("kgcd,ktd->kgct", qg, ck).astype(jnp.float32)
                s_self = jnp.einsum("kgcd,ktd->kgct", qg, k[0]).astype(jnp.float32)
                s_ctx = jnp.where(ctx_live[None, None, None], s_ctx * scale, -jnp.inf)
                s_self = jnp.where(causal[None, None], s_self * scale, -jnp.inf)
                probs = jax.nn.softmax(
                    jnp.concatenate([s_ctx, s_self], axis=-1), axis=-1)
                p_ctx = probs[..., :max_ctx].astype(c.dtype)
                p_self = probs[..., max_ctx:].astype(c.dtype)
                attn = jnp.einsum("kgct,ktd->kgcd", p_ctx, cv) + jnp.einsum(
                    "kgct,ktd->kgcd", p_self, v[0])
                attn = attn.reshape(1, c.n_heads, C, c.head_dim)
                out = jnp.einsum("bhsd,hde->bse", attn, layer["wo"])
                if lora_local is not None:
                    from .lora import lora_delta_single

                    flat = jnp.swapaxes(attn, 1, 2).reshape(1, C, -1)
                    out = out + lora_delta_single(
                        flat, lora_local["wo.A"], lora_local["wo.B"],
                        l, lslot).astype(out.dtype)
                x2 = _mlp(xc + out, layer, c)
                # Guarded ROW-granular scatter: row j of the chunk lands
                # at (page of position start+j, its offset) — mid-page
                # starts never clobber a COW fork's copied prefix rows.
                # Stages without the real chunk write the OLD rows back
                # (branchless no-op, exactly like the old page write).
                k_new = jnp.swapaxes(k[0], 0, 1)       # [C, KH, D]
                v_new = jnp.swapaxes(v[0], 0, 1)
                kp = kp.at[l, write_pages, :, write_offs, :].set(
                    jnp.where(live, k_new,
                              kp[l, write_pages, :, write_offs, :]))
                vp = vp.at[l, write_pages, :, write_offs, :].set(
                    jnp.where(live, v_new,
                              vp[l, write_pages, :, write_offs, :]))
                return (x2, kp, vp), None

            n_local = kp.shape[0]
            (x, kp, vp), _ = lax.scan(
                body, (x, kp, vp), (layers_local, jnp.arange(n_local)))
            h = rms_norm(x, final_norm, eps=c.norm_eps)[0]   # [C, E]
            hidden = jnp.where(live & (stage == pp - 1), h, hidden)
            act = lax.ppermute(x, "pp", perm=perm)
            return (act, hidden, kp, vp), None

        hidden0 = jnp.zeros((C, c.hidden), c.dtype)
        act0 = jnp.zeros((1, C, c.hidden), c.dtype)
        (_, hidden, kp, vp), _ = lax.scan(
            tick, (act0, hidden0, kp, vp), jnp.arange(pp))
        hidden = lax.psum(
            jnp.where(stage == pp - 1, hidden, jnp.zeros_like(hidden)), "pp")
        return {"k": kp, "v": vp}, hidden

    layer_spec = jax.tree.map(lambda _: P("pp"), params["layers"])
    # Manual over pp ONLY: tp stays an auto axis, so XLA partitions the
    # per-stage math from the params' tp shardings (TP inside PP stages
    # — the composition the reference gets from vLLM, vllm_models.py:117).
    args = [params["layers"], pages["k"], pages["v"], params["embed"],
            params["final_norm"], block_table, tokens, start_pos]
    specs = [layer_spec, P("pp"), P("pp"), P(), P(), P(), P(), P()]
    if lora is not None:
        args += [lora, lora_slot]
        specs += [jax.tree.map(lambda _: P("pp"), lora), P()]
    fn = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=tuple(specs),
        out_specs=({"k": P("pp"), "v": P("pp")}, P()),
        axis_names=frozenset({"pp"}),
        check_vma=False,
    )
    return fn(*args)


@functools.partial(jax.jit,
                   static_argnames=("config", "page_size", "n_steps", "mesh",
                                    "paged", "live_pages"),
                   donate_argnames=("pages",))
def pp_decode_loop(params, pages, block_tables, tokens, pos, temps, eos_ids,
                   remaining, key, config: LlamaConfig, page_size: int,
                   n_steps: int, mesh, paged: bool = False,
                   live_pages: int | None = None, lora=None, lora_idx=None):
    """Pipelined ``decode_loop``: same contract (tokens [n_steps, slots],
    key, pages). ``slots`` must divide into ``pp`` groups; group ``g``'s
    round ``r`` runs on stage ``s`` at tick ``t = g + r*pp + s``, so all
    stages stay busy after a (pp-1)-tick warmup.

    ``paged=True`` runs the v2 staging-buffer schedule INSIDE the
    pipeline (ROADMAP item 4's second half): each stage's LOCAL layer
    shard of the pool stays strictly read-only across all ticks, group
    ``g``'s round-``r`` K/V lands in staging row ``r`` of a per-group
    staging carry (guarded so warmup/cooldown ticks never clobber live
    rows — ``decode_block(stage_live=...)``), the Pallas kernel folds
    rows [0, r] as its second KV source exactly as unpipelined, and ONE
    per-stage ``commit_staging`` scatter writes everything back at the
    dispatch boundary. ``live_pages`` bounds the kernel grid by POOL
    context only (staged tokens never touch the pool mid-dispatch).

    Composed pp×tp meshes (round 15): with ``paged=True`` and ``tp`` >
    1 the region is manual over BOTH axes — pp on layers, tp on KV
    heads — because the opaque kernel cannot sit under an auto-tp
    partition. Per-leaf in_specs carry the params' real tp axes
    (``_manual_layer_specs``), ``decode_block``/``_mlp`` psum the two
    row-parallel projections over ``tp_axis="tp"``, and the per-shard
    logits ``all_gather`` (tiled, vocab-shard order) before sampling so
    every device samples the identical token. Dense decode keeps the
    old manual-pp-only region with tp auto.

    ``lora``/``lora_idx`` thread the device-resident adapter stacks
    through the pipeline: the stacks are sharded over ``pp`` on their
    layer axis (matching ``params["layers"]``), so ``decode_block``'s
    local layer index addresses the local stack shard directly.

    Token parity with the unpipelined engine holds for GREEDY decoding
    (temps == 0) only: this loop splits the PRNG key once per pipeline
    tick (T = n_steps*pp + pp - 1 splits) while ``decode_loop`` splits
    once per step, so sampled (temps > 0) outputs draw from the same
    distribution but are not bit-identical."""
    from .model import commit_staging

    c = config
    pp = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    # The kernel forces the composed mesh manual over tp too (see module
    # docstring); dense tp stays an auto axis exactly as before.
    tp_manual = bool(paged and tp > 1)
    tp_axis = "tp" if tp_manual else None
    slots = tokens.shape[0]
    m = slots // pp
    maxp = block_tables.shape[1]
    T = n_steps * pp + pp - 1

    bt_g = block_tables.reshape(pp, m, maxp)
    tok_g = tokens.reshape(pp, m)
    pos_g = pos.reshape(pp, m)
    temp_g = temps.reshape(pp, m)
    eos_g = eos_ids.reshape(pp, m)
    rem_g = remaining.reshape(pp, m)
    idx_g = None if lora_idx is None else lora_idx.reshape(pp, m)
    # slot i's trash page is page i (the unpipelined decode_loop invariant)
    trash_g = jnp.arange(slots, dtype=jnp.int32).reshape(pp, m)

    def per_device(layers_local, kp, vp, embed, final_norm, lm_head,
                   bt_g, tok_g, pos_g, temp_g, eos_g, rem_g, pos0, key,
                   lora_local=None, idx_g=None):
        stage = lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        n_local = kp.shape[0]  # this stage's layer count
        kh_local = kp.shape[2]  # KV heads (a tp shard when tp is manual)
        if paged:
            from ..ops.paged_attention import stage_rows

            sc = stage_rows(n_steps)
            # Per-GROUP staging carry [Ll, pp, m, KHl, SC, D]: group g's
            # row r holds position pos0_g + r (LOCAL layers AND local KV
            # heads — pool shard and staging shard stay aligned).
            stage_shape = (n_local, pp, m, kh_local, sc, c.head_dim)
            ks0 = jnp.zeros(stage_shape, kp.dtype)
            vs0 = jnp.zeros(stage_shape, vp.dtype)
        else:
            ks0 = vs0 = jnp.zeros((0,), c.dtype)  # unused carry filler

        def tick(carry, t):
            rot, outputs, widx_all, kp, vp, ks, vs, key = carry
            g = (t - stage) % pp
            roundr = (t - stage) // pp
            live_round = (t >= stage) & (roundr < n_steps)
            rc = jnp.clip(roundr, 0, n_steps - 1)
            inject = (stage == 0) & (t < pp)           # group g's first visit
            tok_in = jnp.where(inject, tok_g[g], rot["tok"])
            cpos = jnp.where(inject, pos_g[g], rot["pos"])
            crem = jnp.where(inject, rem_g[g], rot["rem"])
            cdone = jnp.where(inject, rem_g[g] <= 0, rot["done"])
            done_eff = cdone | ~live_round
            bt = bt_g[g]
            lidx = None if idx_g is None else idx_g[g]
            emb = embed[tok_in][:, None].astype(c.dtype)       # [m, 1, E]
            x = jnp.where(stage == 0, emb, rot["act"])
            real_page = jnp.take_along_axis(
                bt, jnp.minimum(cpos // page_size, maxp - 1)[:, None],
                axis=1)[:, 0]
            write_idx = jnp.where(done_eff, trash_g[g], real_page)
            # Paged: the kernel reads pool [0, cpos - rc) — the group's
            # dispatch-entry context — plus this group's staged rows
            # [0, rc]; the pool shard is NEVER written inside the scan.
            stage_g = (ks[:, g], vs[:, g]) if paged else None

            def body(carry, xs):
                xc, kp, vp, stg = carry
                layer, l = xs
                x2, kp, vp, stg = decode_block(
                    xc, layer, kp, vp, l, bt, cpos, write_idx, c, page_size,
                    paged=paged, live_pages=live_pages if paged else None,
                    lora=lora_local, lora_idx=lidx,
                    stage=stg, stage_step=rc if paged else None,
                    stage_live=live_round if paged else None,
                    tp_axis=tp_axis)
                return (x2, kp, vp, stg), None

            (x, kp, vp, stage_g), _ = lax.scan(
                body, (x, kp, vp, stage_g),
                (layers_local, jnp.arange(n_local)))
            if paged:
                ks = ks.at[:, g].set(stage_g[0])
                vs = vs.at[:, g].set(stage_g[1])
                widx_all = widx_all.at[rc, g].set(
                    jnp.where(live_round, write_idx, widx_all[rc, g]))

            # Last stage: logits + sample (computed on every stage for
            # SPMD uniformity; only the last stage's result is used).
            hidden = rms_norm(x, final_norm, eps=c.norm_eps)
            logits = jnp.einsum(
                "bse,ev->bsv", hidden, lm_head)[:, 0].astype(jnp.float32)
            if tp_manual:
                # lm_head is vocab-sharded over tp inside the manual
                # region: gather the shards (tiled = vocab order) so
                # argmax/categorical see the full distribution and every
                # device samples the identical token.
                logits = lax.all_gather(logits, "tp", axis=1, tiled=True)
            key, sub = jax.random.split(key)
            greedy = jnp.argmax(logits, axis=-1)
            temps_c = temp_g[g]
            sampled = jax.random.categorical(
                sub, logits / jnp.maximum(temps_c, 1e-6)[:, None])
            new_tok = jnp.where(temps_c > 0.0, sampled, greedy).astype(jnp.int32)
            rem2 = crem - jnp.where(done_eff, 0, 1)
            done2 = done_eff | (new_tok == eos_g[g]) | (rem2 <= 0)

            is_last = stage == pp - 1
            ok = live_round & is_last
            vals = jnp.where(ok, new_tok, outputs[rc, g])
            outputs = outputs.at[rc, g].set(vals)

            rot_next = {
                "act": x,
                "tok": jnp.where(is_last, new_tok, tok_in),
                "pos": jnp.where(is_last, cpos + 1, cpos),
                "rem": jnp.where(is_last, rem2, crem),
                "done": jnp.where(is_last, done2, cdone),
            }
            rot_next = lax.ppermute(rot_next, "pp", perm=perm)
            return (rot_next, outputs, widx_all, kp, vp, ks, vs, key), None

        rot0 = {
            "act": jnp.zeros((m, 1, c.hidden), c.dtype),
            "tok": jnp.zeros((m,), jnp.int32),
            "pos": jnp.zeros((m,), jnp.int32),
            "rem": jnp.zeros((m,), jnp.int32),
            "done": jnp.zeros((m,), bool),
        }
        outputs0 = jnp.zeros((n_steps, pp, m), jnp.int32)
        widx0 = jnp.zeros((n_steps, pp, m), jnp.int32)
        (_, outputs, widx_all, kp, vp, ks, vs, key), _ = lax.scan(
            tick, (rot0, outputs0, widx0, kp, vp, ks0, vs0, key),
            jnp.arange(T))
        if paged:
            # The one pool write of the whole dispatch, per stage over its
            # LOCAL layers: regroup the per-group staging carry back to
            # slot order and commit (mirrors decode_loop + commit_staging).
            ks_flat = ks.reshape(n_local, slots, kh_local,
                                 ks.shape[4], c.head_dim)
            vs_flat = vs.reshape(n_local, slots, kh_local,
                                 vs.shape[4], c.head_dim)
            committed = commit_staging(
                {"k": kp, "v": vp}, (ks_flat, vs_flat),
                widx_all.reshape(n_steps, slots), pos0, n_steps, page_size)
            kp, vp = committed["k"], committed["v"]
        outputs = lax.psum(
            jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)), "pp")
        return outputs.reshape(n_steps, slots), key, {"k": kp, "v": vp}

    if tp_manual:
        # Flattened manual region: per-leaf specs carry the params' real
        # tp axes (heads/kv_heads/mlp), the pool/staging shard KV heads,
        # lm_head shards vocab. Outputs are tp-invariant (psum'd partials
        # + all-gathered logits), so they stay unsharded in out_specs.
        layer_spec = _manual_layer_specs(config)
        page_spec = P("pp", None, "tp")
        head_spec = P(None, "tp")
        manual_axes = frozenset({"pp", "tp"})
    else:
        layer_spec = jax.tree.map(lambda _: P("pp"), params["layers"])
        page_spec = P("pp")
        head_spec = P()
        manual_axes = frozenset({"pp"})
    args = [params["layers"], pages["k"], pages["v"], params["embed"],
            params["final_norm"], params["lm_head"],
            bt_g, tok_g, pos_g, temp_g, eos_g, rem_g, pos, key]
    specs = [layer_spec, page_spec, page_spec, P(), P(), head_spec,
             P(), P(), P(), P(), P(), P(), P(), P()]
    if lora is not None:
        # Adapter stacks shard over pp on their layer axis, exactly like
        # params["layers"] — local layer indices address them directly.
        # (LoRA never runs under manual tp: the executor refuses
        # lora_config on tp > 1 meshes, so the stacks need no tp specs.)
        args += [lora, idx_g]
        specs += [jax.tree.map(lambda _: P("pp"), lora), P()]
    fn = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=tuple(specs),
        out_specs=(P(), P(), {"k": page_spec, "v": page_spec}),
        axis_names=manual_axes,
        check_vma=False,
    )
    return fn(*args)


@functools.partial(jax.jit,
                   static_argnames=("config", "page_size", "mesh"),
                   donate_argnames=("pages",))
def pp_prefill_chunks(params, pages, block_table, tokens_m, start_pos0,
                      config: LlamaConfig, page_size: int, mesh):
    """CHUNK-PIPELINED prefill: ``m`` consecutive same-size chunks of ONE
    sequence flow through the stages like a wavefront — chunk ``j`` runs
    on stage ``s`` at tick ``t = j + s``, so after a (pp-1)-tick warmup
    every stage computes every tick. The single-chunk schedule
    (``pp_prefill_chunk``) keeps (pp-1)/pp of prefill idle; this one
    approaches full utilization for long prompts (m >= pp). Chunk j+1's
    attention at stage s needs chunk j's stage-s K/V, which stage s wrote
    one tick earlier — the dependency is satisfied by construction.

    tokens_m:   [m, C] int32 — consecutive chunks (C a page multiple;
                ``start_pos0`` itself may be mid-page — rows scatter at
                ``(page, offset)`` granularity since round 15).
    start_pos0: scalar int32 — chunk j starts at ``start_pos0 + j*C``.
    Returns (pages, hidden [m, C, E]).
    """
    c = config
    pp = mesh.shape["pp"]
    m, C = tokens_m.shape
    max_ctx = block_table.shape[0] * page_size
    kh, g = c.n_kv_heads, c.n_heads // c.n_kv_heads
    causal = jnp.arange(C)[:, None] >= jnp.arange(C)[None, :]
    T = m + pp - 1

    def per_device(layers_local, kp, vp, embed, final_norm,
                   block_table, tokens_m, start_pos0):
        stage = lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            act, hiddens, kp, vp = carry
            j = t - stage
            valid = (j >= 0) & (j < m)
            jc = jnp.clip(j, 0, m - 1)
            start_j = start_pos0 + jc * C
            positions = start_j + jnp.arange(C, dtype=jnp.int32)
            ctx_live = jnp.arange(max_ctx, dtype=jnp.int32) < start_j
            # Row-granular destinations (round 15): chunk starts need not
            # be page-aligned — a partial-block prefix hit shifts EVERY
            # chunk of the wavefront mid-page.
            write_pages = block_table[jnp.minimum(
                positions // page_size, block_table.shape[0] - 1)]
            write_offs = positions % page_size
            # stage 0 injects chunk t's embedding at its entry tick
            x0 = embed[tokens_m[jnp.clip(t, 0, m - 1)]][None].astype(c.dtype)
            x = jnp.where((stage == 0) & (t < m), x0, act)

            def body(carry, xs):
                xc, kp, vp = carry
                layer, l = xs
                h = rms_norm(xc, layer["attn_norm"], eps=c.norm_eps)
                q, k, v = _project_qkv(h, layer)
                q = apply_rope(q, positions, theta=c.rope_theta)
                k = apply_rope(k, positions, theta=c.rope_theta)
                ck = _gather_ctx(kp, l, block_table)
                cv = _gather_ctx(vp, l, block_table)
                qg = q[0].reshape(kh, g, C, c.head_dim)
                scale = c.head_dim ** -0.5
                s_ctx = jnp.einsum("kgcd,ktd->kgct", qg, ck).astype(jnp.float32)
                s_self = jnp.einsum("kgcd,ktd->kgct", qg, k[0]).astype(jnp.float32)
                s_ctx = jnp.where(ctx_live[None, None, None], s_ctx * scale, -jnp.inf)
                s_self = jnp.where(causal[None, None], s_self * scale, -jnp.inf)
                probs = jax.nn.softmax(
                    jnp.concatenate([s_ctx, s_self], axis=-1), axis=-1)
                p_ctx = probs[..., :max_ctx].astype(c.dtype)
                p_self = probs[..., max_ctx:].astype(c.dtype)
                attn = jnp.einsum("kgct,ktd->kgcd", p_ctx, cv) + jnp.einsum(
                    "kgct,ktd->kgcd", p_self, v[0])
                attn = attn.reshape(1, c.n_heads, C, c.head_dim)
                out = jnp.einsum("bhsd,hde->bse", attn, layer["wo"])
                x2 = _mlp(xc + out, layer, c)
                k_new = jnp.swapaxes(k[0], 0, 1)       # [C, KH, D]
                v_new = jnp.swapaxes(v[0], 0, 1)
                kp = kp.at[l, write_pages, :, write_offs, :].set(
                    jnp.where(valid, k_new,
                              kp[l, write_pages, :, write_offs, :]))
                vp = vp.at[l, write_pages, :, write_offs, :].set(
                    jnp.where(valid, v_new,
                              vp[l, write_pages, :, write_offs, :]))
                return (x2, kp, vp), None

            (x, kp, vp), _ = lax.scan(
                body, (x, kp, vp), (layers_local, jnp.arange(kp.shape[0])))
            h = rms_norm(x, final_norm, eps=c.norm_eps)[0]   # [C, E]
            hiddens = jnp.where(
                valid & (stage == pp - 1),
                hiddens.at[jc].set(h), hiddens)
            act = lax.ppermute(x, "pp", perm=perm)
            return (act, hiddens, kp, vp), None

        hiddens0 = jnp.zeros((m, C, c.hidden), c.dtype)
        act0 = jnp.zeros((1, C, c.hidden), c.dtype)
        (_, hiddens, kp, vp), _ = lax.scan(
            tick, (act0, hiddens0, kp, vp), jnp.arange(T))
        hiddens = lax.psum(
            jnp.where(stage == pp - 1, hiddens, jnp.zeros_like(hiddens)), "pp")
        return {"k": kp, "v": vp}, hiddens

    layer_spec = jax.tree.map(lambda _: P("pp"), params["layers"])
    fn = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(layer_spec, P("pp"), P("pp"), P(), P(), P(), P(), P()),
        out_specs=({"k": P("pp"), "v": P("pp")}, P()),
        axis_names=frozenset({"pp"}),
        check_vma=False,
    )
    return fn(params["layers"], pages["k"], pages["v"], params["embed"],
              params["final_norm"], block_table, tokens_m, start_pos0)
