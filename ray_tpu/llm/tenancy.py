"""Multi-tenant LoRA multiplexing: tenants, quotas, fair queueing, adapters.

The reference serves many models from one replica fleet by keying each
request off a multiplexed model id (``serve/multiplex`` +
``ray.llm``'s LoRA model loader); this module is the tenancy layer that
turns that id into enforceable per-tenant policy:

- ``TenantSpec`` / ``TenancyConfig`` — declarative per-tenant weight,
  token quota, and the replica-level HBM adapter budget
  (``max_loaded_adapters``).
- ``TokenBucket`` — refill-on-demand token quota; the deficit at refusal
  time yields an HONEST ``Retry-After`` (when the bucket will actually
  cover the request), surfaced as a 429 via ``QuotaExceeded``.
- ``WeightedFairQueue`` — classic virtual-finish-time WFQ algebra used
  by the serve router under saturation: a waiter proceeds only when it
  holds the minimum virtual finish time, so tenants share admitted
  throughput in weight proportion regardless of arrival rates.
- ``AdapterPool`` — per-replica HBM-resident adapter bookkeeping: LRU
  over stack slots with a residency cap (``max_loaded_adapters`` may be
  smaller than the stack's ``max_loras``), pin counts for in-flight
  requests, and load/evict accounting for ``serve.status()``.
- ``TenantLedger`` — per-replica runtime state: tenant resolution,
  quota admission, shed/admit counters, and a windowed TTFT reservoir
  feeding per-tenant p95 rows up the controller probe path.

Everything here is plain host-side Python (no jax imports): the device
work stays in ``lora.py`` / the executor; this module only decides who
gets to use it.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field

DEFAULT_TENANT = "default"


def tenant_of(model_id: str | None) -> str:
    """Canonical tenant key for a request's resolved model id. The empty
    id (base model, no adapter) maps to the shared ``default`` tenant."""
    return model_id if model_id else DEFAULT_TENANT


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's policy row.

    ``weight`` is the WFQ share under saturation (relative, not a
    fraction); ``tokens_per_s`` is the sustained token quota (0 =
    unmetered) with ``burst_tokens`` of credit on top; ``ttft_slo_ms``
    is the tenant's TTFT objective (0 = no SLO) — breaches feed the
    ``slo_burn_frac`` burn-rate row and trigger a flight-recorder
    timeline dump for the breaching request."""

    name: str
    weight: float = 1.0
    tokens_per_s: float = 0.0
    burst_tokens: float = 0.0
    ttft_slo_ms: float = 0.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        if self.tokens_per_s < 0 or self.burst_tokens < 0:
            raise ValueError(f"tenant {self.name!r}: quota must be >= 0")
        if self.ttft_slo_ms < 0:
            raise ValueError(f"tenant {self.name!r}: ttft_slo_ms must be >= 0")


@dataclass(frozen=True)
class TenancyConfig:
    """Deployment-level tenancy policy (rides ``init_kwargs`` so the
    controller can long-poll-publish it to routers)."""

    tenants: tuple[TenantSpec, ...] = ()
    max_loaded_adapters: int = 0   # 0 = no cap below lora max_loras

    @staticmethod
    def from_dict(d: "dict | TenancyConfig | None") -> "TenancyConfig | None":
        if d is None or isinstance(d, TenancyConfig):
            return d
        tenants = []
        for name, spec in (d.get("tenants") or {}).items():
            spec = spec or {}
            tenants.append(TenantSpec(
                name=name,
                weight=float(spec.get("weight", 1.0)),
                tokens_per_s=float(spec.get("tokens_per_s", 0.0)),
                burst_tokens=float(spec.get("burst_tokens", 0.0)),
                ttft_slo_ms=float(spec.get("ttft_slo_ms", 0.0))))
        return TenancyConfig(
            tenants=tuple(tenants),
            max_loaded_adapters=int(d.get("max_loaded_adapters", 0)))

    def spec(self, tenant: str) -> TenantSpec:
        for t in self.tenants:
            if t.name == tenant:
                return t
        return TenantSpec(name=tenant)

    def weights(self) -> dict[str, float]:
        return {t.name: t.weight for t in self.tenants}


class QuotaExceeded(RuntimeError):
    """Tenant token quota exhausted — an HONEST 429: ``retry_after`` is
    when the bucket will actually cover the refused request, not a
    constant. Carried through the replica's streaming error envelope so
    the proxy writes the real status line + Retry-After header."""

    http_status = "429 Too Many Requests"
    reason = "quota_exhausted"

    def __init__(self, message: str, retry_after: int = 1):
        super().__init__(message)
        self.retry_after = max(1, int(retry_after))


class AdapterCapacityError(RuntimeError):
    """Every resident adapter slot is pinned by an in-flight request:
    the engine DEFERS admission (head-of-line wait) instead of failing
    the request — capacity pressure is a queueing condition, not an
    error the client should see."""


class TokenBucket:
    """Refill-on-demand token bucket. Not thread-safe on its own; the
    owning ledger serializes access."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self.level = self.burst
        self._last = time.monotonic()

    def _refill(self, now: float) -> None:
        self.level = min(self.burst, self.level + (now - self._last) * self.rate)
        self._last = now

    def try_acquire(self, tokens: float) -> tuple[bool, int]:
        """(ok, retry_after_s). On refusal retry_after is the honest
        wait until the bucket covers ``tokens`` at the sustained rate."""
        now = time.monotonic()
        self._refill(now)
        if self.level >= tokens:
            self.level -= tokens
            return True, 0
        if self.rate <= 0:
            return False, 60
        deficit = min(tokens, self.burst) - self.level
        return False, max(1, min(60, math.ceil(deficit / self.rate)))

    def charge(self, tokens: float) -> None:
        """Post-hoc debit (generated tokens are only known at finish):
        may drive the level negative, pushing the next refusal out."""
        now = time.monotonic()
        self._refill(now)
        self.level -= tokens


class WeightedFairQueue:
    """Virtual-finish-time weighted fair queueing.

    ``enqueue(tenant, cost)`` stamps a virtual finish time
    ``vft = max(vclock, tenant_last_vft) + cost / weight``; the waiter
    holding the minimum vft is the only one eligible to proceed
    (``is_head``). ``complete`` advances the virtual clock. Under
    saturation this admits token throughput in weight proportion —
    a 2:1 weight split yields a 2:1 admitted-token ratio — while an
    idle tenant's unused share flows to the busy ones (the ``max`` with
    vclock forgives idle time instead of banking it)."""

    def __init__(self, weights: dict[str, float] | None = None):
        self._weights = dict(weights or {})
        self._last_vft: dict[str, float] = {}
        self._vclock = 0.0
        self._seq = 0
        self._pending: dict[int, tuple[float, str]] = {}  # ticket -> (vft, tenant)

    def set_weights(self, weights: dict[str, float]) -> None:
        self._weights = dict(weights or {})

    def weight(self, tenant: str) -> float:
        return max(1e-6, float(self._weights.get(tenant, 1.0)))

    def enqueue(self, tenant: str, cost: float = 1.0) -> int:
        start = max(self._vclock, self._last_vft.get(tenant, 0.0))
        vft = start + max(1e-9, cost) / self.weight(tenant)
        self._last_vft[tenant] = vft
        self._seq += 1
        self._pending[self._seq] = (vft, tenant)
        return self._seq

    def is_head(self, ticket: int) -> bool:
        """True when this ticket holds the minimum (vft, ticket) among
        pending waiters — the only waiter WFQ lets through."""
        if ticket not in self._pending:
            return True
        vft = self._pending[ticket][0]
        best = min((v, t) for t, (v, _) in self._pending.items())
        return (vft, ticket) <= best

    def complete(self, ticket: int) -> None:
        ent = self._pending.pop(ticket, None)
        if ent is not None:
            self._vclock = max(self._vclock, ent[0])

    def cancel(self, ticket: int) -> None:
        """Drop a waiter that was shed/timed out WITHOUT advancing the
        clock past it (its service was never rendered)."""
        ent = self._pending.pop(ticket, None)
        if ent is not None and ent[1] in self._last_vft:
            # Roll the tenant's last vft back if this was its newest
            # stamp, so the shed work doesn't penalize its next arrival.
            if self._last_vft[ent[1]] == ent[0]:
                others = [v for (v, t) in self._pending.values() if t == ent[1]]
                self._last_vft[ent[1]] = max(others) if others else self._vclock

    def pending_by_tenant(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for _v, t in self._pending.values():
            out[t] = out.get(t, 0) + 1
        return out

    def __len__(self) -> int:
        return len(self._pending)


@dataclass
class _AdapterState:
    slot: int
    pins: int = 0
    loads: int = 0
    last_load_ms: float = 0.0


class AdapterPool:
    """HBM-resident adapter bookkeeping for one replica.

    Owns WHICH adapters are resident (LRU over ``capacity`` stack slots,
    at most ``max_resident`` of them occupied at once) and who holds
    pins; the caller owns the actual device write (begin_load /
    commit_load bracket it so a failed load rolls back cleanly).
    Thread-safe."""

    def __init__(self, capacity: int, max_resident: int = 0):
        self.capacity = int(capacity)
        self.max_resident = int(max_resident) if max_resident > 0 \
            else self.capacity
        self.max_resident = min(self.max_resident, self.capacity)
        self._lock = threading.Lock()
        self._resident: dict[str, _AdapterState] = {}   # id -> state
        self._order: list[str] = []                     # LRU, oldest first
        self._free = list(range(1, self.capacity + 1))
        self._loads = 0
        self._evictions = 0
        self._hits = 0
        self._load_ms_total = 0.0
        self._device_unloads = 0
        # Device release hook: ``on_evict(adapter_id, slot)`` fires when
        # an EXPLICIT eviction returns a slot to the free list, so the
        # owner zeroes the stack slot and the HBM is actually reclaimed
        # (LRU replacement inside ``begin_load`` skips it — the incoming
        # adapter's install overwrites the slot immediately anyway).
        self.on_evict = None

    # -- residency -------------------------------------------------------
    def lookup(self, adapter_id: str) -> int | None:
        """Slot if resident (pins it and refreshes LRU), else None."""
        with self._lock:
            st = self._resident.get(adapter_id)
            if st is None:
                return None
            self._order.remove(adapter_id)
            self._order.append(adapter_id)
            st.pins += 1
            self._hits += 1
            return st.slot

    def begin_load(self, adapter_id: str) -> int:
        """Reserve a slot for a cold adapter (evicting an unpinned LRU
        victim if the residency cap is reached). Raises
        ``AdapterCapacityError`` when every resident adapter is pinned.
        The reservation is pinned; finish with ``commit_load`` or
        ``abort_load``."""
        with self._lock:
            if adapter_id in self._resident:
                # Lost a race with a concurrent load: behave like lookup.
                st = self._resident[adapter_id]
                self._order.remove(adapter_id)
                self._order.append(adapter_id)
                st.pins += 1
                return st.slot
            slot = self._claim_slot_locked()
            st = _AdapterState(slot=slot, pins=1)
            self._resident[adapter_id] = st
            self._order.append(adapter_id)
            return slot

    def _claim_slot_locked(self) -> int:
        if self._free and len(self._resident) < self.max_resident:
            return self._free.pop()
        for aid in self._order:                        # oldest first
            st = self._resident[aid]
            if st.pins == 0:
                self._order.remove(aid)
                del self._resident[aid]
                self._evictions += 1
                return st.slot
        raise AdapterCapacityError(
            f"all {len(self._resident)} resident adapters pinned "
            f"(cap {self.max_resident} of {self.capacity} slots); "
            "admission defers until a request finishes")

    def commit_load(self, adapter_id: str, load_ms: float = 0.0) -> None:
        with self._lock:
            st = self._resident.get(adapter_id)
            if st is not None:
                st.loads += 1
                st.last_load_ms = load_ms
                self._loads += 1
                self._load_ms_total += load_ms

    def abort_load(self, adapter_id: str) -> None:
        """Roll back a begin_load whose device write failed."""
        with self._lock:
            st = self._resident.pop(adapter_id, None)
            if st is None:
                return
            if adapter_id in self._order:
                self._order.remove(adapter_id)
            st.pins -= 1
            if st.pins <= 0:
                self._free.append(st.slot)
            else:
                # Another request pinned mid-load; it will fail on its
                # own — still return the slot once pins drain via unpin.
                self._resident[adapter_id] = st
                self._order.append(adapter_id)

    def unpin(self, adapter_id: str) -> None:
        with self._lock:
            st = self._resident.get(adapter_id)
            if st is not None and st.pins > 0:
                st.pins -= 1

    def unpin_slot(self, slot: int) -> None:
        with self._lock:
            for st in self._resident.values():
                if st.slot == slot and st.pins > 0:
                    st.pins -= 1
                    return

    # -- explicit eviction (idle-adapter device unload) -----------------
    def evict(self, adapter_id: str) -> int | None:
        """Evict one UNPINNED resident adapter and return its slot to
        the free list — then fire ``on_evict`` (outside the lock) so the
        device stack slot is zeroed, not left holding stale weights
        until some future load recycles it. Returns the freed slot, or
        None when the adapter is absent or pinned."""
        with self._lock:
            st = self._resident.get(adapter_id)
            if st is None or st.pins > 0:
                return None
            self._order.remove(adapter_id)
            del self._resident[adapter_id]
            self._free.append(st.slot)
            self._evictions += 1
            self._device_unloads += 1
            slot = st.slot
        if self.on_evict is not None:
            try:
                self.on_evict(adapter_id, slot)
            except Exception:
                pass
        return slot

    def evict_idle(self) -> list[tuple[str, int]]:
        """Evict EVERY unpinned resident adapter (fleet scale-to-zero:
        an idle replica hands its whole adapter stack's HBM back).
        Returns the ``(adapter_id, slot)`` pairs released."""
        with self._lock:
            victims = [aid for aid in list(self._order)
                       if self._resident[aid].pins == 0]
        out = []
        for aid in victims:
            slot = self.evict(aid)
            if slot is not None:
                out.append((aid, slot))
        return out

    # -- introspection ---------------------------------------------------
    def resident(self) -> dict[str, int]:
        """adapter_id -> slot, LRU order (oldest first)."""
        with self._lock:
            return {aid: self._resident[aid].slot for aid in self._order}

    def pinned(self) -> dict[str, int]:
        with self._lock:
            return {aid: st.pins for aid, st in self._resident.items()
                    if st.pins > 0}

    def stats(self) -> dict:
        with self._lock:
            return {
                "resident": list(self._order),
                "resident_count": len(self._resident),
                "max_resident": self.max_resident,
                "capacity": self.capacity,
                "hits": self._hits,
                "loads": self._loads,
                "evictions": self._evictions,
                # HBM-slot accounting: slots genuinely free (zeroed or
                # never used) vs merely recyclable, and how many
                # evictions actually released device memory.
                "free_slots": len(self._free),
                "device_unloads": self._device_unloads,
                "avg_load_ms": (self._load_ms_total / self._loads
                                if self._loads else 0.0),
            }


@dataclass
class _TenantState:
    spec: TenantSpec
    bucket: TokenBucket | None = None
    admitted: int = 0
    shed: int = 0
    quota_rejects: int = 0
    tokens_in: int = 0
    tokens_out: int = 0
    ttft_ms: deque = field(default_factory=lambda: deque(maxlen=256))
    # Windowed SLO breach flags (parallel window to ttft_ms): burn
    # fraction = mean over the reservoir, so it recovers as the window
    # rolls — a burn-rate, not a lifetime counter.
    slo_window: deque = field(default_factory=lambda: deque(maxlen=256))
    slo_breaches: int = 0
    # EWMA of actual_cost / estimated_cost at retire: >1 means the
    # prompt+max_tokens heuristic UNDER-charges this tenant's WFQ share.
    cost_ratio: float = 1.0
    cost_samples: int = 0


class TenantLedger:
    """Per-replica tenant runtime: quota admission + counters + windowed
    TTFT reservoir. Thread-safe; cheap enough to sit on the request
    path."""

    def __init__(self, config: TenancyConfig | None = None):
        self.config = config or TenancyConfig()
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}

    def _state_locked(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            spec = self.config.spec(tenant)
            bucket = (TokenBucket(spec.tokens_per_s, spec.burst_tokens)
                      if spec.tokens_per_s > 0 else None)
            st = _TenantState(spec=spec, bucket=bucket)
            self._tenants[tenant] = st
        return st

    def admit(self, tenant: str, tokens: int) -> None:
        """Charge ``tokens`` (prompt + max_new worst case) against the
        tenant's quota; raises ``QuotaExceeded`` (honest 429) when the
        bucket can't cover it."""
        with self._lock:
            st = self._state_locked(tenant)
            if st.bucket is not None:
                ok, retry_after = st.bucket.try_acquire(tokens)
                if not ok:
                    st.quota_rejects += 1
                    raise QuotaExceeded(
                        f"tenant {tenant!r} token quota exhausted "
                        f"({st.spec.tokens_per_s:g} tok/s); retry in "
                        f"{retry_after}s", retry_after=retry_after)
            st.admitted += 1
            st.tokens_in += tokens

    def note_shed(self, tenant: str) -> None:
        with self._lock:
            self._state_locked(tenant).shed += 1

    def note_tokens(self, tenant: str, generated: int) -> None:
        with self._lock:
            self._state_locked(tenant).tokens_out += generated

    def note_ttft(self, tenant: str, ttft_ms: float) -> bool:
        """Record one TTFT sample; returns True when it breached the
        tenant's ``ttft_slo_ms`` (callers use that to trigger the
        flight-recorder dump for the breaching request)."""
        with self._lock:
            st = self._state_locked(tenant)
            st.ttft_ms.append(float(ttft_ms))
            slo = st.spec.ttft_slo_ms
            if slo <= 0:
                return False
            breached = ttft_ms > slo
            st.slo_window.append(1 if breached else 0)
            if breached:
                st.slo_breaches += 1
            return breached

    def slo_burn_frac(self, tenant: str) -> float:
        """Fraction of the windowed TTFT reservoir that breached the
        tenant's SLO (0.0 when no SLO configured or no samples yet)."""
        with self._lock:
            st = self._state_locked(tenant)
            if not st.slo_window:
                return 0.0
            return sum(st.slo_window) / len(st.slo_window)

    def note_actual(self, tenant: str, estimated: float, actual: float) -> None:
        """Retire-time WFQ cost correction: fold actual/estimated into
        the tenant's EWMA ratio. The router scales this tenant's future
        cost estimates by the published ratio, so tenants whose requests
        systematically overrun (or undershoot) their ``max_tokens``
        heuristic still get charged their true share."""
        if estimated <= 0:
            return
        ratio = max(0.01, min(100.0, float(actual) / float(estimated)))
        with self._lock:
            st = self._state_locked(tenant)
            if st.cost_samples == 0:
                st.cost_ratio = ratio
            else:
                st.cost_ratio = 0.8 * st.cost_ratio + 0.2 * ratio
            st.cost_samples += 1

    def quota_remaining(self, tenant: str) -> float | None:
        with self._lock:
            st = self._state_locked(tenant)
            if st.bucket is None:
                return None
            st.bucket._refill(time.monotonic())
            return max(0.0, st.bucket.level)

    def snapshot(self) -> dict:
        """Per-tenant rows for ``latency_snapshot`` / ``serve.status()``:
        counters are cumulative, p95 is over the windowed reservoir."""
        with self._lock:
            out = {}
            for name, st in self._tenants.items():
                vals = sorted(st.ttft_ms)
                p95 = vals[max(0, math.ceil(0.95 * len(vals)) - 1)] \
                    if vals else 0.0
                row = {"admitted": st.admitted, "shed": st.shed,
                       "quota_rejects": st.quota_rejects,
                       "tokens_in": st.tokens_in,
                       "tokens_out": st.tokens_out,
                       "weight": st.spec.weight,
                       "p95_ttft_ms": round(p95, 3)}
                if st.cost_samples:
                    row["cost_correction"] = round(st.cost_ratio, 4)
                if st.spec.ttft_slo_ms > 0:
                    row["ttft_slo_ms"] = st.spec.ttft_slo_ms
                    row["slo_breaches"] = st.slo_breaches
                    row["slo_burn_frac"] = round(
                        sum(st.slo_window) / len(st.slo_window), 4) \
                        if st.slo_window else 0.0
                if st.bucket is not None:
                    st.bucket._refill(time.monotonic())
                    row["quota_remaining"] = round(max(0.0, st.bucket.level), 1)
                    row["tokens_per_s"] = st.spec.tokens_per_s
                out[name] = row
            return out
