"""Multi-host LLM engine: one inference engine spanning hosts.

Reference: ``python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:117-168`` — the reference places TP×PP vLLM engines across
nodes via placement-group bundles (STRICT_PACK when the engine fits one
node, PACK otherwise). TPU redesign (SURVEY.md §7.1): one
``EngineShardWorker`` actor per host, bootstrapped with
``jax.distributed.initialize`` (the same SPMD↔actor bridge Train uses,
``train/worker_group.py``), each holding a ``LocalEngineExecutor`` built
over the GLOBAL mesh. The engine scheduler stays wherever the Serve
replica lives and fans each step plan out to every shard; every shard
executes the SAME jitted program in the same order, and XLA inserts the
tensor-parallel collectives over ICI/DCN. Only small host arrays (block
tables, token ids) cross the actor boundary — the params and KV pages
never leave the shards.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..core import api as ray


class EngineShardWorker:
    """Actor hosting one process (one host's chips) of the sharded engine."""

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world
        self.executor = None

    def coordinator_address(self) -> str:
        from ..parallel.distributed import pick_coordinator_address

        return pick_coordinator_address()

    def init_distributed(self, coordinator: str) -> int:
        from ..parallel.distributed import initialize_process

        return initialize_process(coordinator, self.world, self.rank)

    def build(self, config, *, max_slots: int, num_pages: int, page_size: int,
              tp: int | None = None, pp: int | None = None, seed: int = 0,
              attention_impl: str = "auto") -> int:
        """Create the executor over the global mesh (all hosts' devices).
        Default tp = every device in the group (pure TP); pass ``pp`` to
        stage layers across hosts instead (pure PP this round).
        ``attention_impl="auto"`` resolves per shard exactly as on a
        single host: the paged kernel shard_maps over the kv-head/tp
        axis, dense for pp meshes."""
        import jax

        from ..parallel import MeshConfig, create_mesh
        from .executor import LocalEngineExecutor

        n = len(jax.devices())
        pp = pp or 1
        # tp composes inside pp stages (partial-manual shard_map in
        # pp_model.py); with pp given, default tp fills the remaining
        # devices. Pure TP (pp=1) defaults to tp over every device.
        tp = tp or max(1, n // pp)
        mesh = create_mesh(MeshConfig(tp=tp, pp=pp, dp=max(1, n // (tp * pp))))
        self.executor = LocalEngineExecutor(
            config, max_slots=max_slots, num_pages=num_pages,
            page_size=page_size, mesh=mesh, seed=seed,
            attention_impl=attention_impl,
        )
        return n

    # ------------------------------------------------ executor operations
    def prefill(self, block_table, tokens, start_pos, handle, take) -> bool:
        self.executor.prefill(block_table, tokens, start_pos, handle, take)
        return True

    def drop_handle(self, handle) -> bool:
        self.executor.drop_handle(handle)
        return True

    def sample_first(self, handles, temps):
        return self.executor.sample_first(handles, temps)

    def decode(self, block_tables, tokens, pos, temps, eos_ids, remaining,
               n_steps):
        return self.executor.decode(
            block_tables, tokens, pos, temps, eos_ids, remaining, n_steps)

    def supports_mixed(self) -> bool:
        return bool(self.executor is not None
                    and self.executor.supports_mixed_dispatch)

    def mixed(self, prefill_plans, block_tables, tokens, pos, temps, eos_ids,
              remaining, n_steps):
        return self.executor.mixed(
            prefill_plans, block_tables, tokens, pos, temps, eos_ids,
            remaining, n_steps)


class ShardedEngineExecutor:
    """Driver-side executor fanning every operation out to the shard
    actors (duck-types ``LocalEngineExecutor``). Actor-call ordering per
    caller guarantees every shard sees the identical program sequence —
    the SPMD invariant."""

    def __init__(self, shards: list, pg=None):
        self.shards = shards
        self._pg = pg
        self._pending: list = []  # in-flight async dispatches (prefill/drop)
        # Set after build() by create_sharded_executor: whether every
        # shard's local executor takes the fused mixed entry point.
        self.supports_mixed_dispatch = False

    def _dispatch(self, method: str, *args) -> None:
        """Fire-and-forget to every shard: per-caller actor ordering keeps
        the program sequence identical on all shards, so prefill chunks
        need no host sync (mirroring LocalEngineExecutor's pure-dispatch
        prefill — one blocking round trip per CHUNK would wreck TTFT).
        Errors surface at the next sync point."""
        self._pending.extend(
            getattr(s, method).remote(*args) for s in self.shards)

    def _sync(self, timeout: float = 300.0) -> None:
        if self._pending:
            pending, self._pending = self._pending, []
            ray.get(pending, timeout=timeout)

    def _all(self, method: str, *args, timeout: float = 300.0):
        self._sync(timeout)
        refs = [getattr(s, method).remote(*args) for s in self.shards]
        return ray.get(refs, timeout=timeout)

    def prefill(self, block_table, tokens, start_pos, handle, take,
                lora_slot: int = 0) -> None:
        # lora is single-device-executor only; the engine never routes
        # adapter requests here (admission fails them without a manager)
        self._dispatch("prefill", block_table, tokens, start_pos, handle, take)

    def drop_handle(self, handle) -> None:
        self._dispatch("drop_handle", handle)

    def sample_first(self, handles, temps) -> np.ndarray:
        return self._all("sample_first", list(handles), temps)[0]

    def decode(self, block_tables, tokens, pos, temps, eos_ids, remaining,
               n_steps, lora_idx=None) -> np.ndarray:
        return self._all(
            "decode", block_tables, tokens, pos, temps, eos_ids, remaining,
            n_steps)[0]

    def mixed(self, prefill_plans, block_tables, tokens, pos, temps, eos_ids,
              remaining, n_steps, lora_idx=None) -> np.ndarray:
        """Fused prefill+decode step on every shard: each shard stashes
        final-chunk hiddens under the same handles, so a later
        ``sample_first`` fan-out finds them everywhere (the SPMD
        invariant — identical program sequence per shard)."""
        return self._all(
            "mixed", prefill_plans, block_tables, tokens, pos, temps,
            eos_ids, remaining, n_steps)[0]

    def shutdown(self) -> None:
        for s in self.shards:
            try:
                ray.kill(s)
            except Exception:
                pass
        if self._pg is not None:
            from ..util import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass


def create_sharded_executor(
    config,
    num_hosts: int,
    *,
    max_slots: int,
    num_pages: int,
    page_size: int,
    tp: int | None = None,
    pp: int | None = None,
    seed: int = 0,
    bundle_resources: dict | None = None,
    topology: str | None = None,
    strategy: str | None = None,
    runtime_env: dict | None = None,
    attention_impl: str = "auto",
) -> ShardedEngineExecutor:
    """Place one shard actor per host and bootstrap the group.

    ``bundle_resources``: per-host bundle (e.g. ``{"TPU": 4, "CPU": 1}``).
    ``topology``: TPU slice type (e.g. ``v5litepod-16``) — claims the
    slice-head resource on bundle 0 so the whole slice is ours atomically.
    ``strategy``: placement strategy; defaults to the reference's choice —
    STRICT_PACK for a single-host engine, PACK across hosts
    (``vllm_models.py:131-168``).
    """
    from ..util import PlacementGroupSchedulingStrategy, placement_group, remove_placement_group

    res = dict(bundle_resources or {"CPU": 1.0})
    bundles = [dict(res) for _ in range(num_hosts)]
    if topology:
        bundles[0][f"TPU-{topology}-head"] = 1.0
    strategy = strategy or ("STRICT_PACK" if num_hosts == 1 else "PACK")
    pg = placement_group(bundles, strategy=strategy)
    if not pg.wait(timeout_seconds=120.0):
        remove_placement_group(pg)
        raise TimeoutError(
            f"placement group for {num_hosts} engine shards not ready in 120s")
    actor_cls = ray.remote(EngineShardWorker)
    shards = [
        actor_cls.options(
            resources=dict(bundles[i]),
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i),
            runtime_env=runtime_env,
        ).remote(i, num_hosts)
        for i in range(num_hosts)
    ]
    executor = ShardedEngineExecutor(shards, pg)
    try:
        coordinator = ray.get(shards[0].coordinator_address.remote(), timeout=120)
        ray.get([s.init_distributed.remote(coordinator) for s in shards],
                timeout=300)
        ray.get([
            s.build.remote(config, max_slots=max_slots, num_pages=num_pages,
                           page_size=page_size, tp=tp, pp=pp, seed=seed,
                           attention_impl=attention_impl)
            for s in shards
        ], timeout=600)
        executor.supports_mixed_dispatch = bool(ray.get(
            shards[0].supports_mixed.remote(), timeout=60))
    except Exception:
        executor.shutdown()
        raise
    return executor
