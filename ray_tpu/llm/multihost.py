"""Multi-host LLM engine: one inference engine spanning hosts.

Reference: ``python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:117-168`` — the reference places TP×PP vLLM engines across
nodes via placement-group bundles (STRICT_PACK when the engine fits one
node, PACK otherwise). TPU redesign (SURVEY.md §7.1): one
``EngineShardWorker`` actor per host, bootstrapped with
``jax.distributed.initialize`` (the same SPMD↔actor bridge Train uses,
``train/worker_group.py``), each holding a ``LocalEngineExecutor`` built
over the GLOBAL mesh. The engine scheduler stays wherever the Serve
replica lives and fans each step plan out to every shard; every shard
executes the SAME jitted program in the same order, and XLA inserts the
tensor-parallel collectives over ICI/DCN. Only small host arrays (block
tables, token ids) cross the actor boundary — the params and KV pages
never leave the shards.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ..core import api as ray


class EngineShardWorker:
    """Actor hosting one process (one host's chips) of the sharded engine."""

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world
        self.executor = None

    def coordinator_address(self) -> str:
        from ..parallel.distributed import pick_coordinator_address

        return pick_coordinator_address()

    def init_distributed(self, coordinator: str) -> int:
        from ..parallel.distributed import initialize_process

        return initialize_process(coordinator, self.world, self.rank)

    def build(self, config, *, max_slots: int, num_pages: int, page_size: int,
              tp: int | None = None, pp: int | None = None, seed: int = 0,
              attention_impl: str = "auto", lora_config=None) -> int:
        """Create the executor over the global mesh (all hosts' devices).
        Default tp = every device in the group (pure TP); pass ``pp`` to
        stage layers across hosts instead.
        ``attention_impl="auto"`` resolves per shard exactly as on a
        single host: the paged kernel shard_maps over the kv-head/tp
        axis, rides the pp tick loop's staging carry, and on composed
        pp x tp meshes runs inside the flattened {"pp","tp"} manual
        region (round 15) — no mesh shape resolves dense on a TPU
        backend anymore. ``lora_config`` builds the device-resident
        adapter stacks on every shard (pp-sharded over the layer axis
        on pipeline meshes)."""
        import jax

        from ..parallel import MeshConfig, create_mesh
        from .executor import LocalEngineExecutor

        n = len(jax.devices())
        pp = pp or 1
        # tp composes inside pp stages (partial-manual shard_map in
        # pp_model.py); with pp given, default tp fills the remaining
        # devices. Pure TP (pp=1) defaults to tp over every device.
        tp = tp or max(1, n // pp)
        mesh = create_mesh(MeshConfig(tp=tp, pp=pp, dp=max(1, n // (tp * pp))))
        self.executor = LocalEngineExecutor(
            config, max_slots=max_slots, num_pages=num_pages,
            page_size=page_size, mesh=mesh, seed=seed,
            attention_impl=attention_impl, lora_config=lora_config,
        )
        return n

    # ------------------------------------------------ executor operations
    def tick(self, plan):
        """Compiled-loop entry point: ONE method drives every engine
        operation so a resident loop executor (dag/loop.py) can stream
        step plans over a channel with zero per-tick RPC. ``plan`` is
        ``(method_name, args)``; per-channel FIFO ordering preserves the
        SPMD invariant exactly like per-caller actor ordering did."""
        method, args = plan
        return getattr(self, method)(*args)

    def prefill(self, block_table, tokens, start_pos, handle, take,
                lora_slot=0) -> bool:
        self.executor.prefill(block_table, tokens, start_pos, handle, take,
                              lora_slot=lora_slot)
        return True

    def drop_handle(self, handle) -> bool:
        self.executor.drop_handle(handle)
        return True

    def install_adapter(self, slot, arrays) -> bool:
        self.executor.install_adapter(slot, arrays)
        return True

    def sample_first(self, handles, temps):
        return self.executor.sample_first(handles, temps)

    def decode(self, block_tables, tokens, pos, temps, eos_ids, remaining,
               n_steps, lora_idx=None):
        return self.executor.decode(
            block_tables, tokens, pos, temps, eos_ids, remaining, n_steps,
            lora_idx=lora_idx)

    def supports_mixed(self) -> bool:
        return bool(self.executor is not None
                    and self.executor.supports_mixed_dispatch)

    def supports_spec(self) -> bool:
        return bool(self.executor is not None
                    and self.executor.supports_speculation)

    def verify(self, block_tables, tokens_mat, pos, temps, eos_ids,
               remaining):
        """Speculative verify on this shard: every shard scores the same
        drafted batch (SPMD), so the fan-out's first result is the
        group's answer."""
        return self.executor.verify(block_tables, tokens_mat, pos, temps,
                                    eos_ids, remaining)

    def supports_cow(self) -> bool:
        return bool(self.executor is not None
                    and self.executor.supports_prefix_cow)

    def copy_pages(self, src, dst) -> bool:
        self.executor.copy_pages(src, dst)
        return True

    def supports_migration(self) -> bool:
        """KV page export/import. Single-host groups only for now: a
        multi-process mesh shards the pool across hosts, so one shard
        cannot materialize the full [L, m, ...] page payload (residue —
        a per-shard chunked wire format would lift this)."""
        return bool(self.executor is not None and self.world == 1
                    and getattr(self.executor, "supports_kv_migration",
                                False))

    def export_pages(self, page_ids):
        return self.executor.export_pages(page_ids)

    def import_pages(self, page_ids, data) -> bool:
        self.executor.import_pages(page_ids, data)
        return True

    def mixed(self, prefill_plans, block_tables, tokens, pos, temps, eos_ids,
              remaining, n_steps, lora_idx=None):
        return self.executor.mixed(
            prefill_plans, block_tables, tokens, pos, temps, eos_ids,
            remaining, n_steps, lora_idx=lora_idx)


class ShardedEngineExecutor:
    """Driver-side executor fanning every operation out to the shard
    actors (duck-types ``LocalEngineExecutor``).

    Two dispatch modes, IDENTICAL program sequence per shard (the SPMD
    invariant) in both:

      * **dynamic** (default off the pp path): one actor call per shard
        per operation — per-caller actor ordering sequences the shards.
        Every steady-state decode burst pays the full submit→lease→push
        RPC path per shard.
      * **compiled loop** (``use_compiled_loop=True``; the default the
        pp tick path gets from ``create_sharded_executor``): ONE
        owner-side submit per shard installs a resident
        ``EngineShardWorker.tick`` executor (``dag/loop.py``), and every
        operation afterwards is a channel write — ``put((method, args))``
        — with results streamed back in order. Zero per-tick task
        submission, RPC, or lease traffic at steady state; channel FIFO
        ordering replaces actor-call ordering. Fire-and-forget
        operations (prefill chunks, drop_handle) pipeline up to the
        loop's credits ahead of their results, mirroring the dynamic
        ``_dispatch``'s pure-dispatch behavior.
    """

    def __init__(self, shards: list, pg=None, use_compiled_loop: bool = False):
        self.shards = shards
        self._pg = pg
        self._pending: list = []  # in-flight async dispatches (prefill/drop)
        self._loop = None
        self._loop_pending = 0    # loop results put but not yet consumed
        self.use_compiled_loop = use_compiled_loop
        # Set after build() by create_sharded_executor: whether every
        # shard's local executor takes the fused mixed entry point /
        # the COW prefix-sharing ops / the KV-migration page ops.
        self.supports_mixed_dispatch = False
        self.supports_prefix_cow = False
        self.supports_kv_migration = False
        self.supports_speculation = False
        # Serializes each operation's whole per-shard dispatch sequence:
        # KV imports/exports arrive on REQUEST threads while the engine
        # loop keeps fanning steps out, and an interleave inside one
        # operation's shard sequence would break the SPMD program-order
        # invariant (and corrupt the compiled loop's channel FIFO).
        self._dispatch_lock = threading.RLock()

    # ---------------------------------------------------- compiled loop
    def _ensure_loop(self):
        if self._loop is None:
            from ..dag import InputNode, MultiOutputNode, compile_loop

            with InputNode() as inp:
                outs = [s.tick.bind(inp) for s in self.shards]
            graph = outs[0] if len(outs) == 1 else MultiOutputNode(outs)
            self._loop = compile_loop(graph)
        return self._loop

    @property
    def loop_ticks(self) -> int:
        """Engine ticks streamed through the compiled loop so far."""
        return self._loop._gets if self._loop is not None else 0

    def _loop_put(self, method: str, *args) -> None:
        self._ensure_loop().put((method, tuple(args)), timeout=300.0)
        self._loop_pending += 1

    def _loop_drain(self, keep_last: bool, timeout: float = 300.0):
        """Consume queued results in order; returns the LAST one (the
        per-shard result list) when ``keep_last``."""
        last = None
        while self._loop_pending:
            self._loop_pending -= 1
            got = self._loop.get(timeout=timeout)
            if keep_last and not self._loop_pending:
                last = got if isinstance(got, tuple) else (got,)
        return last

    # --------------------------------------------------------- dispatch
    def _dispatch(self, method: str, *args) -> None:
        """Fire-and-forget to every shard: ordering (actor-call or loop
        channel FIFO) keeps the program sequence identical on all
        shards, so prefill chunks need no host sync (mirroring
        LocalEngineExecutor's pure-dispatch prefill — one blocking round
        trip per CHUNK would wreck TTFT). Errors surface at the next
        sync point."""
        with self._dispatch_lock:
            if self.use_compiled_loop:
                self._loop_put(method, *args)
                return
            self._pending.extend(
                getattr(s, method).remote(*args) for s in self.shards)

    def _sync(self, timeout: float = 300.0) -> None:
        with self._dispatch_lock:
            if self.use_compiled_loop:
                self._loop_drain(keep_last=False, timeout=timeout)
                return
            if self._pending:
                pending, self._pending = self._pending, []
                ray.get(pending, timeout=timeout)

    def _all(self, method: str, *args, timeout: float = 300.0):
        with self._dispatch_lock:
            if self.use_compiled_loop:
                self._loop_drain(keep_last=False, timeout=timeout)
                self._loop_put(method, *args)
                return list(self._loop_drain(keep_last=True, timeout=timeout))
            self._sync(timeout)
            refs = [getattr(s, method).remote(*args) for s in self.shards]
            return ray.get(refs, timeout=timeout)

    def prefill(self, block_table, tokens, start_pos, handle, take,
                lora_slot: int = 0) -> None:
        self._dispatch("prefill", block_table, tokens, start_pos, handle,
                       take, int(lora_slot))

    def drop_handle(self, handle) -> None:
        self._dispatch("drop_handle", handle)

    def copy_pages(self, src, dst) -> None:
        """COW fork fan-out: rides the ordered dispatch stream, so every
        shard copies the page before the chunk that writes into it."""
        self._dispatch("copy_pages",
                       [int(s) for s in src], [int(d) for d in dst])

    def export_pages(self, page_ids) -> dict:
        """KV-migration export: one shard's full-pool gather (single-host
        groups — see ``EngineShardWorker.supports_migration``). Rides the
        ordered stream so every prior prefill write is visible."""
        return self._all("export_pages", [int(p) for p in page_ids])[0]

    def import_pages(self, page_ids, data) -> None:
        """KV-migration import fan-out, ordered with the dispatch stream
        so no shard can read the pages before the scatter lands."""
        self._dispatch("import_pages", [int(p) for p in page_ids],
                       {k: np.asarray(v) for k, v in data.items()})

    def install_adapter(self, slot, arrays) -> None:
        """LoRA fan-out: the adapter's padded A/B arrays land on every
        shard's device stack, ORDERED with the prefill/decode stream so
        no shard can run a step before the adapter its plan references
        is installed."""
        self._dispatch("install_adapter", int(slot),
                       {k: np.asarray(v) for k, v in arrays.items()})

    def sample_first(self, handles, temps) -> np.ndarray:
        return self._all("sample_first", list(handles), temps)[0]

    def decode(self, block_tables, tokens, pos, temps, eos_ids, remaining,
               n_steps, lora_idx=None) -> np.ndarray:
        return self._all(
            "decode", block_tables, tokens, pos, temps, eos_ids, remaining,
            n_steps, lora_idx)[0]

    def verify(self, block_tables, tokens_mat, pos, temps, eos_ids,
               remaining):
        """Speculative verify fan-out: every shard runs the SAME verify
        program in sequence with the rest of the dispatch stream (SPMD
        invariant), over actor calls or the compiled loop's channel
        identically; shard 0's (tokens, live) is the group's result."""
        return self._all(
            "verify", block_tables, tokens_mat, pos, temps, eos_ids,
            remaining)[0]

    def mixed(self, prefill_plans, block_tables, tokens, pos, temps, eos_ids,
              remaining, n_steps, lora_idx=None) -> np.ndarray:
        """Fused prefill+decode step on every shard: each shard stashes
        final-chunk hiddens under the same handles, so a later
        ``sample_first`` fan-out finds them everywhere (the SPMD
        invariant — identical program sequence per shard)."""
        return self._all(
            "mixed", prefill_plans, block_tables, tokens, pos, temps,
            eos_ids, remaining, n_steps, lora_idx)[0]

    def shutdown(self) -> None:
        if self._loop is not None:
            try:
                self._loop.teardown(timeout=10.0)
            except Exception:
                pass
            self._loop = None
        for s in self.shards:
            try:
                ray.kill(s)
            except Exception:
                pass
        if self._pg is not None:
            from ..util import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass


def create_sharded_executor(
    config,
    num_hosts: int,
    *,
    max_slots: int,
    num_pages: int,
    page_size: int,
    tp: int | None = None,
    pp: int | None = None,
    seed: int = 0,
    bundle_resources: dict | None = None,
    topology: str | None = None,
    strategy: str | None = None,
    runtime_env: dict | None = None,
    attention_impl: str = "auto",
    lora_config=None,
    use_compiled_loop: bool | None = None,
) -> ShardedEngineExecutor:
    """Place one shard actor per host and bootstrap the group.

    ``bundle_resources``: per-host bundle (e.g. ``{"TPU": 4, "CPU": 1}``).
    ``topology``: TPU slice type (e.g. ``v5litepod-16``) — claims the
    slice-head resource on bundle 0 so the whole slice is ours atomically.
    ``strategy``: placement strategy; defaults to the reference's choice —
    STRICT_PACK for a single-host engine, PACK across hosts
    (``vllm_models.py:131-168``).
    ``use_compiled_loop``: drive the steady-state engine tick path
    through a persistent compiled loop (``dag/loop.py``) instead of one
    actor RPC per shard per operation. Default: ON for pipeline meshes
    (``pp`` > 1) — the per-tick dispatch overhead the static schedule
    exists to kill — OFF otherwise (pass ``True`` to force it anywhere).
    """
    if use_compiled_loop is None:
        use_compiled_loop = bool(pp and pp > 1)
    from ..util import PlacementGroupSchedulingStrategy, placement_group, remove_placement_group

    res = dict(bundle_resources or {"CPU": 1.0})
    bundles = [dict(res) for _ in range(num_hosts)]
    if topology:
        bundles[0][f"TPU-{topology}-head"] = 1.0
    strategy = strategy or ("STRICT_PACK" if num_hosts == 1 else "PACK")
    pg = placement_group(bundles, strategy=strategy)
    if not pg.wait(timeout_seconds=120.0):
        remove_placement_group(pg)
        raise TimeoutError(
            f"placement group for {num_hosts} engine shards not ready in 120s")
    actor_cls = ray.remote(EngineShardWorker)
    shards = [
        actor_cls.options(
            resources=dict(bundles[i]),
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i),
            runtime_env=runtime_env,
        ).remote(i, num_hosts)
        for i in range(num_hosts)
    ]
    executor = ShardedEngineExecutor(shards, pg,
                                     use_compiled_loop=use_compiled_loop)
    try:
        coordinator = ray.get(shards[0].coordinator_address.remote(), timeout=120)
        ray.get([s.init_distributed.remote(coordinator) for s in shards],
                timeout=300)
        ray.get([
            s.build.remote(config, max_slots=max_slots, num_pages=num_pages,
                           page_size=page_size, tp=tp, pp=pp, seed=seed,
                           attention_impl=attention_impl,
                           lora_config=lora_config)
            for s in shards
        ], timeout=600)
        executor.supports_mixed_dispatch = bool(ray.get(
            shards[0].supports_mixed.remote(), timeout=60))
        executor.supports_prefix_cow = bool(ray.get(
            shards[0].supports_cow.remote(), timeout=60))
        executor.supports_kv_migration = bool(ray.get(
            shards[0].supports_migration.remote(), timeout=60))
        executor.supports_speculation = bool(ray.get(
            shards[0].supports_spec.remote(), timeout=60))
        if use_compiled_loop:
            # Install the resident tick executors NOW (one submit per
            # shard — the last tasks this executor ever submits).
            executor._ensure_loop()
    except Exception:
        executor.shutdown()
        raise
    return executor
