"""Batch LLM inference over Data: the Processor pipeline.

Equivalent of the reference's
``python/ray/llm/_internal/batch/processor/base.py`` (``Processor`` /
``ProcessorConfig`` / ``build_llm_processor``): a composable stage
pipeline over a ``Dataset`` — preprocess → continuous-batching LLM
inference on stateful engine actors → postprocess. The inference stage
is a ``map_batches`` over an actor pool whose workers each own an
``InferenceEngine``; every batch's prompts are admitted TOGETHER so the
engine's continuous batching (shared decode steps, paged KV, prefix
reuse) applies within the batch — the reference gets this from vLLM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class LLMProcessorConfig:
    """Engine + stage settings (reference ``ProcessorConfig`` +
    ``vLLMEngineProcessorConfig``)."""

    preset: str = "debug-128"
    concurrency: int = 1          # engine actors in the pool
    batch_size: int = 16          # prompts per map_batches call
    max_slots: int = 8
    max_len: int = 256
    page_size: int = 16
    prefill_chunk_size: int = 64
    decode_steps_per_dispatch: int = 8
    # sampling defaults (overridable per-row via a "sampling_params" column)
    max_tokens: int = 32
    temperature: float = 0.0
    # TPU placement: set True to give each engine actor a TPU chip.
    use_tpu: bool = False
    seed: int = 0


class _EngineWorker:
    """One engine actor of the inference stage: constructed once per
    actor (model init + compile happen once), then every batch flows
    through continuous batching."""

    def __init__(self, config: LLMProcessorConfig):
        from .engine import InferenceEngine, Request
        from .tokenizer import ByteTokenizer

        self._Request = Request
        self.engine = InferenceEngine(
            config.preset,
            max_slots=config.max_slots,
            max_len=config.max_len,
            page_size=config.page_size,
            prefill_chunk_size=config.prefill_chunk_size,
            decode_steps_per_dispatch=config.decode_steps_per_dispatch,
            seed=config.seed,
        )
        self.tokenizer = ByteTokenizer()
        self.config = config
        self._counter = 0

    def __call__(self, batch: dict) -> dict:
        prompts = [str(p) for p in batch["prompt"]]
        max_tokens_col = batch.get("max_tokens")
        temp_col = batch.get("temperature")
        reqs = []
        for i, prompt in enumerate(prompts):
            self._counter += 1
            req = self._Request(
                f"batch-{self._counter}",
                self.tokenizer.encode(prompt),
                int(max_tokens_col[i]) if max_tokens_col is not None
                else self.config.max_tokens,
                float(temp_col[i]) if temp_col is not None
                else self.config.temperature,
                eos_id=self.tokenizer.eos_id,
            )
            reqs.append(req)
            self.engine.add_request(req)
        # Drive the shared continuous-batching loop until this batch is
        # fully decoded (other prompts keep the decode batch full).
        while not all(r.done for r in reqs):
            self.engine.step()
        out = dict(batch)
        out["generated_text"] = [self.tokenizer.decode(r.generated) for r in reqs]
        out["num_generated_tokens"] = [len(r.generated) for r in reqs]
        return out


class Processor:
    """A runnable pipeline: ``processor(ds)`` returns the transformed
    Dataset (reference ``Processor.__call__``)."""

    def __init__(self, config: LLMProcessorConfig,
                 preprocess: Callable | None = None,
                 postprocess: Callable | None = None):
        self.config = config
        self._pre = preprocess
        self._post = postprocess

    def __call__(self, ds):
        from ..data import ActorPoolStrategy

        if self._pre is not None:
            ds = ds.map(self._pre)
        ds = ds.map_batches(
            _EngineWorker,
            batch_format="numpy",
            compute=ActorPoolStrategy(size=self.config.concurrency),
            fn_constructor_args=(self.config,),
            ray_actor_options=(
                {"resources": {"TPU": 1}} if self.config.use_tpu else None),
        )
        if self._post is not None:
            ds = ds.map(self._post)
        return ds


def build_llm_processor(config: LLMProcessorConfig,
                        preprocess: Callable | None = None,
                        postprocess: Callable | None = None) -> Processor:
    """Reference ``build_llm_processor``: rows in, rows with
    ``generated_text`` out. ``preprocess`` maps a row to include a
    ``prompt`` (and optional ``sampling_params``); ``postprocess`` maps
    the generated row to its final shape."""
    return Processor(config, preprocess, postprocess)
