"""LLM serving deployment: the engine behind a Serve replica.

Equivalent of the reference's ``LLMServer``
(``python/ray/llm/_internal/serve/deployments/llm/llm_server.py:415``):
one engine per replica, concurrent HTTP/handle requests feed the shared
continuous-batching loop, and each caller blocks only on its own
completion. Scale-out happens at the Serve layer (num_replicas), exactly
as the reference scales vLLM engine replicas.
"""

from __future__ import annotations

import threading
import time

from .engine import InferenceEngine, Request
from .tokenizer import ByteTokenizer


class LLMDeployment:
    """User-facing deployment class: wrap with ``serve.deployment`` (see
    ``build_llm_app``). Methods run on replica executor threads; one
    background thread drives the engine so requests batch continuously."""

    def __init__(
        self,
        preset: str = "debug-128",
        *,
        max_slots: int = 8,
        max_len: int = 256,
        seed: int = 0,
        request_timeout_s: float = 300.0,
    ):
        self.engine = InferenceEngine(preset, max_slots=max_slots, max_len=max_len, seed=seed)
        self.tokenizer = ByteTokenizer()
        if self.tokenizer.vocab_size > self.engine.config.vocab_size:
            raise ValueError(
                f"model vocab {self.engine.config.vocab_size} is smaller than "
                f"tokenizer vocab {self.tokenizer.vocab_size}; pick a preset "
                f"with vocab_size >= {self.tokenizer.vocab_size}"
            )
        self.request_timeout_s = request_timeout_s
        self._events: dict[str, threading.Event] = {}
        self._counter = 0
        self._lock = threading.Lock()
        self._running = True
        self._loop_thread = threading.Thread(target=self._engine_loop, daemon=True)
        self._loop_thread.start()

    def _engine_loop(self) -> None:
        while self._running:
            if not self.engine.has_work:
                time.sleep(0.002)
                continue
            for event in self.engine.step():
                if event["done"]:
                    done = self._events.pop(event["request_id"], None)
                    if done is not None:
                        done.set()

    def close(self) -> None:
        """Stop the engine loop. Serve replica teardown kills the worker
        process anyway; this exists for in-process reuse (tests, notebooks)
        — the loop thread holds a ref to self, so __del__ alone would never
        fire."""
        self._running = False
        if self._loop_thread.is_alive():
            self._loop_thread.join(timeout=5)

    # --------------------------------------------------------------- methods
    def generate(self, prompt: str, max_new_tokens: int = 16,
                 temperature: float = 0.0) -> dict:
        """Blocking completion; many calls run concurrently on replica
        threads and share the engine's decode batch."""
        ids = self.tokenizer.encode(prompt)
        with self._lock:
            self._counter += 1
            rid = f"req-{self._counter}"
        req = Request(rid, ids, max_new_tokens, temperature,
                      eos_id=self.tokenizer.eos_id)
        done = threading.Event()
        self._events[rid] = done
        self.engine.add_request(req)
        if not done.wait(timeout=self.request_timeout_s):
            # Cancel so the engine stops mutating req and the slot frees;
            # drop our event entry (the loop pops it only on completion).
            self.engine.cancel(rid)
            self._events.pop(rid, None)
            return {
                "request_id": rid,
                "text": self.tokenizer.decode(req.generated),
                "tokens": list(req.generated),
                "finish_reason": "timeout",
                "num_generated": len(req.generated),
            }
        return {
            "request_id": rid,
            "text": self.tokenizer.decode(req.generated),
            "tokens": list(req.generated),
            "finish_reason": req.finish_reason,
            "num_generated": len(req.generated),
        }

    def __call__(self, request) -> dict:
        """HTTP entrypoint: /app?prompt=...&max_new_tokens=N."""
        q = request.query_params
        return self.generate(
            q.get("prompt", ""),
            max_new_tokens=int(q.get("max_new_tokens", 16)),
            temperature=float(q.get("temperature", 0.0)),
        )


def build_llm_app(preset: str = "debug-128", *, num_replicas: int = 1,
                  max_slots: int = 8, max_len: int = 256,
                  max_ongoing_requests: int = 32):
    """Build a Serve Application serving ``preset`` (serve.run-able)."""
    from ..serve import deployment

    dep = deployment(
        LLMDeployment,
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
    )
    return dep.bind(preset, max_slots=max_slots, max_len=max_len)
