"""LLM serving deployment: OpenAI-compatible API over the paged engine.

Equivalent of the reference's ``LLMServer`` + OpenAI router
(``python/ray/llm/_internal/serve/deployments/llm/llm_server.py:415``,
``.../routers/router.py:173``): one engine per replica, concurrent
HTTP/handle requests feed the shared continuous-batching loop, and
``/v1/completions`` + ``/v1/chat/completions`` (with ``"stream": true``
SSE token streaming) ride the Serve streaming request path. Scale-out
happens at the Serve layer (num_replicas), exactly as the reference
scales vLLM engine replicas.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from collections import OrderedDict

from .engine import InferenceEngine, Request
from .tokenizer import ByteTokenizer

# Request-level serving metrics (lazily created so importing llm doesn't
# start the metrics flusher). serve_ttft_ms is the measurement ROADMAP
# item 2 was missing: arrival → first sampled token, tagged with the
# serve deployment hosting the engine (falls back to the model id when
# the engine runs outside serve).
_metrics_lock = threading.Lock()
_metrics: dict = {}


def _llm_metrics() -> dict:
    with _metrics_lock:
        if not _metrics:
            from ..util.metrics import Gauge, Histogram

            _metrics["ttft"] = Histogram(
                "serve_ttft_ms",
                "Time from request arrival to first generated token",
                tag_keys=("deployment", "tenant"))
            _metrics["prefix_hit_rate"] = Gauge(
                "serve_prefix_cache_hit_rate",
                "Fraction of cacheable prompt pages served from the "
                "engine's prefix cache (0-1, since engine start)",
                tag_keys=("deployment",))
            _metrics["slo_burn"] = Gauge(
                "tenant_slo_burn_frac",
                "Fraction of the tenant's windowed TTFT samples that "
                "breached its ttft_slo_ms objective (0-1; 0 when no SLO "
                "is configured)",
                tag_keys=("deployment", "tenant"))
        return _metrics


def _deployment_tag(fallback: str) -> str:
    try:
        from ..serve.replica import get_replica_context

        rc = get_replica_context()
        if rc and rc.get("deployment"):
            return rc["deployment"]
    except Exception:
        pass
    return fallback


def _observe_ttft(req: Request, deployment: str, engine=None,
                  tenant: str = "default", ledger=None) -> None:
    if req.first_token_at is None:
        return
    ttft_ms = 1000.0 * (req.first_token_at - req.arrived_at)
    _llm_metrics()["ttft"].observe(
        ttft_ms, tags={"deployment": deployment, "tenant": tenant})
    if ledger is not None:
        breached = ledger.note_ttft(tenant, ttft_ms)
        _llm_metrics()["slo_burn"].set(
            ledger.slo_burn_frac(tenant),
            tags={"deployment": deployment, "tenant": tenant})
        if breached and engine is not None:
            # SLO breach: dump the request's flight-recorder timeline
            # (at most once per request) so the slow path is replayable
            # via `cli trace --request`.
            try:
                engine.dump_timeline(req, "ttft_slo")
            except Exception:
                pass
    if engine is not None:
        _llm_metrics()["prefix_hit_rate"].set(
            engine.prefix_cache_hit_rate, tags={"deployment": deployment})


class LLMDeployment:
    """User-facing deployment class: wrap with ``serve.deployment`` (see
    ``build_llm_app``). Methods run on replica executor threads; one
    background thread drives the engine so requests batch continuously."""

    # Thread-local handoff marker: _import_migration stamps the KV token
    # count here so the request object created later ON THE SAME THREAD
    # gets an EV_MIGRATE flight-recorder event.
    _migrate_tls = threading.local()

    def __init__(
        self,
        preset: str = "debug-128",
        *,
        model_id: str | None = None,
        max_slots: int = 8,
        max_len: int = 256,
        page_size: int = 16,
        prefill_chunk_size: int = 64,
        decode_steps_per_dispatch: int = 8,
        tensor_parallel: int = 1,
        pipeline_parallel: int = 1,
        num_hosts: int = 1,
        shard_resources: dict | None = None,
        shard_runtime_env: dict | None = None,
        topology: str | None = None,
        seed: int = 0,
        request_timeout_s: float = 300.0,
        lora_config: dict | None = None,
        attention_impl: str = "auto",
        prefill_token_budget: int | None = None,
        max_prefill_seqs_per_step: int = 2,
        decode_starvation_limit: int = 8,
        use_compiled_loop: bool | None = None,
        role: str = "unified",
        decode_handle=None,
        host_kv_cache_pages: int = 0,
        max_queued_requests: int = 0,
        admission_watermark_pages: int | None = None,
        speculation_config=None,
        tenancy_config: dict | None = None,
    ):
        from .tenancy import TenancyConfig, TenantLedger

        mesh = None
        executor = None
        self._sharded = None
        # Multi-tenant policy: per-tenant quotas/weights + the replica's
        # HBM adapter residency cap. The same dict rides init_kwargs so
        # the controller publishes the WEIGHTS to routers via long poll;
        # this replica enforces the QUOTAS and reports per-tenant rows.
        tcfg = TenancyConfig.from_dict(tenancy_config) or TenancyConfig()
        self.tenancy = TenantLedger(tcfg)
        lora = None
        if lora_config is not None:
            # Reference: LLMConfig.lora_config + dynamic_lora_loading_path
            # (configs/server_models.py:141,236). Requests whose `model`
            # differs from the base model_id load that adapter from
            # `<dynamic_lora_loading_path>/<model>.npz` into the device
            # stack and decode with it (multi-adapter batching).
            from .lora import LoRAServingConfig

            lc = dict(lora_config)
            # Tenancy's HBM residency cap applies to the adapter LRU
            # unless the lora config pins its own.
            if tcfg.max_loaded_adapters and "max_loaded_adapters" not in lc:
                lc["max_loaded_adapters"] = tcfg.max_loaded_adapters
            lora = LoRAServingConfig(**lc)
        if num_hosts > 1 or shard_resources is not None:
            # Replica-spans-hosts: one engine-shard actor per host placed
            # by a placement group, jax.distributed across them, the
            # scheduler here fanning step plans out (reference:
            # vllm_models.py:117-168 TP×PP placement; SURVEY §7.1 bridge).
            # On the pp tick path the steady-state fan-out rides a
            # persistent compiled loop (dag/loop.py) instead of per-tick
            # actor RPC (use_compiled_loop defaults on for pp > 1).
            from .multihost import create_sharded_executor

            executor = self._sharded = create_sharded_executor(
                preset, num_hosts,
                max_slots=max_slots,
                num_pages=InferenceEngine.total_pages(max_slots, max_len, page_size),
                page_size=page_size,
                tp=tensor_parallel if tensor_parallel > 1 else None,
                pp=pipeline_parallel if pipeline_parallel > 1 else None,
                seed=seed,
                bundle_resources=shard_resources,
                topology=topology,
                runtime_env=shard_runtime_env,
                attention_impl=attention_impl,
                lora_config=lora,
                use_compiled_loop=use_compiled_loop,
            )
        elif tensor_parallel > 1 or pipeline_parallel > 1:
            # Shard the engine across this replica's visible chips (e.g.
            # the 4/8 chips of a TPU host): tp runs the same programs
            # SPMD with XLA collectives over ICI; pp stages layers with
            # ppermute activation rotation (llm/pp_model.py).
            import jax

            from ..parallel import MeshConfig, create_mesh

            n = len(jax.devices())
            mesh = create_mesh(MeshConfig(
                tp=tensor_parallel, pp=pipeline_parallel,
                dp=max(1, n // (tensor_parallel * pipeline_parallel))))
        self.engine = InferenceEngine(
            preset, max_slots=max_slots, max_len=max_len, page_size=page_size,
            prefill_chunk_size=prefill_chunk_size,
            decode_steps_per_dispatch=decode_steps_per_dispatch, mesh=mesh,
            executor=executor, seed=seed, lora_config=lora,
            attention_impl=attention_impl,
            prefill_token_budget=prefill_token_budget,
            max_prefill_seqs_per_step=max_prefill_seqs_per_step,
            decode_starvation_limit=decode_starvation_limit,
            host_kv_cache_pages=host_kv_cache_pages,
            max_queued_requests=max_queued_requests,
            admission_watermark_pages=admission_watermark_pages,
            speculation_config=speculation_config,
        )
        # Disaggregated serving (DistServe-style prefill/decode split):
        # a "prefill"-role replica chunk-prefills prompts locally, ships
        # the KV pages to a decode replica over a migration stream, and
        # relays the decode replica's token stream; "decode" replicas
        # additionally accept migrated handoffs. "unified" (default) is
        # the classic one-pool deployment.
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown role {role!r}")
        self._role = role
        self._decode_handle = decode_handle
        if role == "prefill" and decode_handle is None:
            raise ValueError("role='prefill' needs a decode_handle")
        self.model_id = model_id or (preset if isinstance(preset, str) else "custom")
        self.tokenizer = ByteTokenizer()
        if self.tokenizer.vocab_size > self.engine.config.vocab_size:
            raise ValueError(
                f"model vocab {self.engine.config.vocab_size} is smaller than "
                f"tokenizer vocab {self.tokenizer.vocab_size}; pick a preset "
                f"with vocab_size >= {self.tokenizer.vocab_size}"
            )
        self.request_timeout_s = request_timeout_s
        # Prefix-group residency: which affinity groups this replica's
        # engine holds KV for, and how often their requests actually hit
        # the prefix cache — reported to the controller through the
        # replica's latency_snapshot probe (serve_prefix_residency row).
        self._residency_lock = threading.Lock()
        self._resident_groups: "OrderedDict[str, int]" = OrderedDict()
        self._residency = {"requests": 0, "cache_hits": 0}
        # Completion waiters (blocking path) and per-request token queues
        # (streaming path), both fed by the engine loop.
        self._events: dict[str, threading.Event] = {}
        self._token_queues: dict[str, queue.Queue] = {}
        self._counter = 0
        self._lock = threading.Lock()
        # Spill-migration exporters opened FOR remote pullers (reaped as
        # their streams drain — see _track_spill_source).
        self._spill_sources: list = []
        # Always-warm fleet: request-idle clock (scale-to-zero input)
        # and the seed that reproduces this deployment's weights for the
        # promotion ladder's last-resort cold re-init.
        self._last_request_ts = time.time()
        self._seed = seed
        self._running = True
        self._loop_thread = threading.Thread(target=self._engine_loop, daemon=True)
        self._loop_thread.start()

    def _engine_loop(self) -> None:
        while self._running:
            if not self.engine.has_work:
                time.sleep(0.002)
                continue
            for event in self.engine.step():
                q = self._token_queues.get(event["request_id"])
                if q is not None:
                    q.put(event)
                if event["done"]:
                    done = self._events.pop(event["request_id"], None)
                    if done is not None:
                        done.set()

    def close(self) -> None:
        """Stop the engine loop (for in-process reuse — tests, notebooks)."""
        self._running = False
        if self._loop_thread.is_alive():
            self._loop_thread.join(timeout=5)
        if self._sharded is not None:
            self._sharded.shutdown()

    def __del__(self):
        if getattr(self, "_sharded", None) is not None:
            try:
                self._sharded.shutdown()
            except Exception:
                pass

    def _next_rid(self) -> str:
        with self._lock:
            self._counter += 1
            # Every request path mints an rid, so this is the one choke
            # point the fleet idle clock needs.
            self._last_request_ts = time.time()
            return f"req-{self._counter}-{uuid.uuid4().hex[:8]}"

    def _adapter_for(self, model: str | None) -> str | None:
        """OpenAI `model` field -> adapter id (None = base model)."""
        if not model or model == self.model_id:
            return None
        return model

    def _tenant_for(self, model: str | None) -> str:
        """Tenant key for one request: the ``model`` body field, else
        the proxy-resolved multiplexed model id riding the replica
        thread-local, else the shared default tenant."""
        from ..serve.multiplex import get_multiplexed_model_id
        from .tenancy import tenant_of

        return tenant_of(model or get_multiplexed_model_id())

    def _note_residency(self, group: str, req: Request) -> None:
        """Record that this replica now holds (or refreshed) KV for the
        request's prefix group, and whether the request actually hit the
        engine's prefix cache (the replica-local affinity outcome)."""
        if not group:
            return
        with self._residency_lock:
            self._resident_groups[group] = \
                self._resident_groups.get(group, 0) + 1
            self._resident_groups.move_to_end(group)
            while len(self._resident_groups) > 512:
                self._resident_groups.popitem(last=False)
            self._residency["requests"] += 1
            if req.cached_prefix_tokens > 0:
                self._residency["cache_hits"] += 1

    def prefix_residency(self) -> dict:
        """Per-replica prefix-group residency (picked up by the replica
        actor's ``latency_snapshot`` probe → controller app status)."""
        with self._residency_lock:
            return {"groups": len(self._resident_groups),
                    "requests": self._residency["requests"],
                    "cache_hits": self._residency["cache_hits"]}

    @staticmethod
    def _group_of(prompt: str, session_id: str | None) -> str:
        from ..serve.router import prefix_group_key

        return prefix_group_key(session_id=str(session_id or ""),
                                text=prompt)

    @staticmethod
    def _effective_deadline(body: dict | None = None) -> float | None:
        """The request's absolute wall-clock deadline: the proxy-stamped
        value riding the replica thread-local, tightened by a
        ``timeout_s`` body field when the request arrived by handle
        (no proxy hop to stamp it)."""
        from ..serve.router import get_request_deadline

        deadline = get_request_deadline()
        t = (body or {}).get("timeout_s")
        if t is not None:
            try:
                local = time.time() + max(0.0, float(t))
                deadline = local if deadline is None else min(deadline, local)
            except (TypeError, ValueError):
                pass
        return deadline

    # ------------------------------------------------------ blocking path
    def generate(self, prompt: str, max_new_tokens: int = 16,
                 temperature: float = 0.0, model: str | None = None,
                 session_id: str | None = None,
                 deadline: float | None = None) -> dict:
        """Blocking completion; many calls run concurrently on replica
        threads and share the engine's decode batch. ``model`` other than
        the base model id selects a LoRA adapter. ``deadline`` (absolute
        wall clock; defaults to the proxy-stamped request deadline)
        bounds the request end to end — expiry in the engine queue fails
        fast, expiry mid-decode aborts the slot."""
        self._maybe_spill_migrate(prompt, model)
        if deadline is None:
            deadline = self._effective_deadline()
        ids = self.tokenizer.encode(prompt)
        rid = self._next_rid()
        tenant = self._tenant_for(model)
        # Token quota, charged worst case (prompt + max_new) up front:
        # QuotaExceeded propagates with its own http_status/retry_after,
        # so the proxy answers an honest 429 + Retry-After.
        self.tenancy.admit(tenant, len(ids) + max_new_tokens)
        req = Request(rid, ids, max_new_tokens, temperature,
                      eos_id=self.tokenizer.eos_id,
                      model=self._adapter_for(model),
                      deadline=deadline)
        migrated = getattr(self._migrate_tls, "tokens", None)
        if migrated is not None:
            from ..observability import loop_recorder

            req.timeline.add(loop_recorder.EV_MIGRATE, migrated)
            self._migrate_tls.tokens = None
        done = threading.Event()
        self._events[rid] = done  # before add: the engine may finish fast
        try:
            self.engine.add_request(req)
        except ValueError:
            self._events.pop(rid, None)
            raise
        except Exception:
            self._events.pop(rid, None)
            raise  # QueueFullError: the proxy answers 503 + Retry-After
        timeout = self.request_timeout_s
        if deadline is not None:
            # The engine sweeps expired deadlines each tick; the extra
            # slack only covers the tick boundary.
            timeout = max(0.05, min(timeout, deadline - time.time() + 1.0))
        if not done.wait(timeout=timeout):
            if req.done and req.finish_reason:
                finish = req.finish_reason  # engine settled it (deadline)
            else:
                self.engine.cancel(rid)
                finish = "timeout"
            self._events.pop(rid, None)
        else:
            finish = req.finish_reason
        _observe_ttft(req, _deployment_tag(self.model_id), self.engine,
                      tenant=tenant, ledger=self.tenancy)
        self.tenancy.note_tokens(tenant, len(req.generated))
        # Retire-time WFQ cost correction: the admission estimate charged
        # prompt + max_new worst case; fold the ACTUAL token count into
        # the tenant's EWMA ratio (published to routers via tenancy
        # long-poll) so future estimates converge on reality.
        self.tenancy.note_actual(tenant, len(ids) + max_new_tokens,
                                 len(ids) + len(req.generated))
        self._note_residency(self._group_of(prompt, session_id), req)
        return {
            "request_id": rid,
            "text": self.tokenizer.decode(req.generated),
            "tokens": list(req.generated),
            "finish_reason": finish,
            "num_generated": len(req.generated),
        }

    # ----------------------------------------------------- streaming path
    def _admit_streaming(self, req: Request,
                         tenant: str = "default") -> queue.Queue:
        """Register the token queue and admit ``req``. Split from
        ``_stream_tokens`` so admission — and its QueueFullError /
        QuotaExceeded shed — happens BEFORE the SSE response head is
        yielded: the proxy can then still answer a clean 503/429 +
        Retry-After status line."""
        self.tenancy.admit(tenant, len(req.prompt) + req.max_new_tokens)
        q: queue.Queue = queue.Queue()
        self._token_queues[req.request_id] = q
        try:
            self.engine.add_request(req)
        except Exception:
            self._token_queues.pop(req.request_id, None)
            raise
        return q

    def _stream_tokens(self, req: Request, group: str = "",
                       q: queue.Queue | None = None,
                       tenant: str = "default"):
        """Yield engine events for one request as they are produced; on
        GeneratorExit (consumer gone) cancel the request so its pages and
        slot free immediately."""
        if q is None:
            q = self._admit_streaming(req, tenant)
        deadline = time.monotonic() + self.request_timeout_s
        first = True
        try:
            while True:
                try:
                    event = q.get(timeout=min(5.0, max(0.1, deadline - time.monotonic())))
                except queue.Empty:
                    if time.monotonic() > deadline:
                        self.engine.cancel(req.request_id)
                        return
                    continue
                if first:
                    first = False
                    _observe_ttft(req, _deployment_tag(self.model_id),
                                  self.engine, tenant=tenant,
                                  ledger=self.tenancy)
                    self._note_residency(group, req)
                yield event
                if event["done"]:
                    return
        finally:
            self._token_queues.pop(req.request_id, None)
            self.tenancy.note_tokens(tenant, len(req.generated))
            self.tenancy.note_actual(
                tenant, len(req.prompt) + req.max_new_tokens,
                len(req.prompt) + len(req.generated))
            if not req.done:
                self.engine.cancel(req.request_id)

    # ------------------------------------------------------- OpenAI routes
    def completions(self, body: dict):
        """POST /v1/completions (OpenAI-compatible; reference
        ``routers/router.py:173``). ``"stream": true`` => SSE generator.
        On a prefill-pool replica the request is prefilled locally and
        handed off to a decode replica (``_disagg_request``)."""
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        if self._role == "prefill" and self._decode_handle is not None:
            return self._disagg_request(body, prompt, chat=False)
        return self._local_completion(body, prompt, chat=False)

    def chat_completions(self, body: dict):
        """POST /v1/chat/completions: flatten messages with a minimal
        template, then the completion path."""
        prompt = _render_chat(body.get("messages", []))
        if self._role == "prefill" and self._decode_handle is not None:
            return self._disagg_request(body, prompt, chat=True)
        return self._local_completion(body, prompt, chat=True)

    def _local_completion(self, body: dict, prompt: str, chat: bool):
        """Serve one completion on THIS replica's engine (the unified
        path, and the decode half of a disaggregated handoff)."""
        max_tokens = int(body.get("max_tokens", 16))
        temperature = float(body.get("temperature", 0.0))
        cid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        created = int(time.time())
        if not body.get("stream"):
            out = self.generate(prompt, max_tokens, temperature,
                                model=body.get("model"),
                                session_id=body.get("session_id"),
                                deadline=self._effective_deadline(body))
            usage = {
                "prompt_tokens": len(self.tokenizer.encode(prompt)),
                "completion_tokens": out["num_generated"],
                "total_tokens": len(self.tokenizer.encode(prompt))
                + out["num_generated"],
            }
            if chat:
                return {
                    "id": cid, "object": "chat.completion",
                    "created": created,
                    "model": body.get("model", self.model_id),
                    "choices": [{
                        "index": 0,
                        "message": {"role": "assistant",
                                    "content": out["text"]},
                        "finish_reason": _openai_finish(out["finish_reason"]),
                    }],
                    "usage": usage,
                }
            return {
                "id": cid, "object": "text_completion", "created": created,
                "model": body.get("model", self.model_id),
                "choices": [{
                    "index": 0, "text": out["text"],
                    "finish_reason": _openai_finish(out["finish_reason"]),
                    "logprobs": None,
                }],
                "usage": usage,
            }
        return self._sse_completion_stream(body, prompt, cid, created,
                                           chat=chat)

    # -------------------------------------------- disaggregated serving
    def migrated_completions(self, migration: dict, body: dict):
        """Decode-pool entry point for a disaggregated handoff: pull the
        prefill replica's KV pages over the migration stream (the import
        overlaps the source's still-running prefill), register them, and
        serve the request as an ordinary local completion — admission
        maps the imported prefix, so only the final prompt token's
        hidden state is computed here before decode begins."""
        migration = migration or {}
        chat = bool(migration.get("chat"))
        if chat:
            prompt = _render_chat(body.get("messages", []))
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
        self._import_migration(migration)
        return self._local_completion(body, prompt, chat=chat)

    def _import_migration(self, migration: dict) -> None:
        addr = migration.get("kv_address")
        if not addr or not self.engine.supports_kv_migration:
            return
        t0w = time.time()
        try:
            from .migration import receive_kv_stream

            stats = receive_kv_stream(self.engine, addr)
            attrs = {k: stats.get(k) for k in
                     ("cached_tokens", "pages", "bytes", "seconds",
                      "complete", "status")}
        except Exception as e:  # never fail the request over a transfer
            attrs = {"status": f"{type(e).__name__}: {e}",
                     "complete": False}
        attrs["kind"] = "disagg_handoff"
        if attrs.get("complete"):
            # Mark the NEXT request this thread creates (the migrated
            # completion below) with a flight-recorder migrate event.
            self._migrate_tls.tokens = int(attrs.get("cached_tokens") or 0)
        self._record_kv_migrate_span(t0w, attrs)

    def _disagg_request(self, body: dict, prompt: str, chat: bool):
        """Prefill-pool ingress (DistServe-style split): chunk-prefill
        the prompt on THIS replica (``prefill_only`` — no token is
        sampled), stream its KV pages to a decode replica WHILE later
        chunks are still prefilling, and relay the decode replica's
        response. TTFT-bound prefill and ITL-bound decode never share a
        replica, and the handoff latency hides behind prefill compute.
        If the decode pool is unreachable the request falls back to
        local serving — the prefix just prefilled is cached, so the
        fallback costs one suffix token."""
        from .migration import KVMigrationSource

        ids = self.tokenizer.encode(prompt)
        migration: dict = {"chat": chat}
        src = None
        rid = None
        if self.engine.supports_kv_migration and len(ids) > 1 \
                and not body.get("model"):
            rid = self._next_rid()
            req = Request(rid, list(ids), max_new_tokens=1,
                          prefill_only=True, pin_for_export=True)
            # Invalid prompts raise here, exactly like the local path.
            self.engine.add_request(req)
            try:
                src = KVMigrationSource(self.engine, req)
                migration["kv_address"] = src.address
                migration["prompt_len"] = len(ids)
            except Exception:
                self.engine.cancel(rid)
                src = None
        group = self._group_of(prompt, body.get("session_id"))
        handle = self._decode_handle.options(
            method_name="migrated_completions",
            prefix_group=group or f"mig:{uuid.uuid4().hex[:8]}",
            deadline=self._effective_deadline(body))
        if not body.get("stream"):
            try:
                out = handle.remote(migration, body).result(
                    timeout=self.request_timeout_s)
            except Exception:
                out = None
            finally:
                if src is not None:
                    src.close()
            if out is not None:
                return out
            return self._local_completion(body, prompt, chat)
        try:
            stream = handle.remote_streaming(migration, body)
        except Exception:
            # decode pool unreachable: serve locally off the hot prefix
            if rid is not None:
                self.engine.cancel(rid)
            if src is not None:
                src.close()
            return self._local_completion(body, prompt, chat)

        def relay():
            try:
                for msg in stream:
                    kind = msg.get("kind")
                    if kind == "start":
                        yield {"__serve_response__": True,
                               "content_type": msg.get(
                                   "content_type", "text/event-stream")}
                    elif kind == "chunk":
                        yield msg.get("data", b"")
                    elif kind == "error":
                        raise RuntimeError(msg.get("error", "decode failed"))
                    elif kind == "full":
                        yield json.dumps(msg.get("data")).encode()
            finally:
                try:
                    stream.close()
                except Exception:
                    pass
                if rid is not None:
                    self.engine.cancel(rid)  # no-op once prefilled
                if src is not None:
                    src.close()

        return relay()

    def export_prefix_kv(self, prompt: str, model: str | None = None):
        """Handle/actor entry point: export this replica's cached KV
        covering ``prompt``'s longest prefix as ONE blocking payload
        (``open_prefix_kv_stream`` is the chunked streaming form the
        spill pull uses)."""
        ids = self.tokenizer.encode(prompt)
        return self.engine.export_prefix_kv(ids, self._adapter_for(model))

    def open_prefix_kv_stream(self, prompt: str,
                              model: str | None = None) -> dict | None:
        """Handle/actor entry point (spill migration): open a chunked
        ``KVMigrationSource`` stream over this replica's cached KV
        covering ``prompt``'s longest prefix, so the spill target
        imports chunk-by-chunk — a slow or dying source degrades to the
        received prefix exactly like the disaggregation handoff.
        Returns ``{"kv_address": ...}`` or None when nothing is cached."""
        from .migration import KVMigrationSource

        ids = self.tokenizer.encode(prompt)
        src = KVMigrationSource.for_cached_prefix(
            self.engine, ids, self._adapter_for(model))
        if src is None:
            return None
        self._track_spill_source(src)
        return {"kv_address": src.address}

    def _track_spill_source(self, src) -> None:
        """Keep remotely-opened spill exporters until their streams
        drain, reaping finished ones (and force-closing the oldest past
        the cap) on each new open — the server socket outlives the
        exporter thread until close()."""
        with self._lock:
            sources = getattr(self, "_spill_sources", [])
            keep = []
            for s in sources:
                if s._thread.is_alive() and len(keep) < 7:
                    keep.append(s)
                else:
                    try:
                        s.close()
                    except Exception:
                        pass
            keep.append(src)
            self._spill_sources = keep

    def _maybe_spill_migrate(self, prompt: str,
                             model: str | None = None) -> None:
        """An affinity spill used to throw the group's cached KV away
        (PR-10 residue b): when the router ships the previous affine
        replica's identity with a spilled request, pull the group's hot
        pages from it over the CHUNKED migration stream and import them
        as they arrive — migrate-instead-of-recompute, with
        disaggregation on OR off, degrading to the received prefix when
        the source slows or dies mid-pull. Failure of any step falls
        back to the old behavior (cold prefill)."""
        from ..serve.router import get_migration_source

        src = get_migration_source()
        if not src or not self.engine.supports_kv_migration:
            return
        from ..core.config import get_config

        if not get_config().serve_spill_migration:
            return
        t0w = time.time()
        attrs: dict = {"kind": "spill", "source": src.get("replica_id", "")}
        try:
            from ..core import api as ray
            from ..core.api import ActorHandle

            from .migration import receive_kv_stream

            actor = ActorHandle(bytes.fromhex(src["actor_id"]))
            reply = ray.get(
                actor.handle_request.remote(
                    "open_prefix_kv_stream", (prompt, model), {}),
                timeout=30)
            addr = (reply or {}).get("kv_address")
            if addr:
                stats = receive_kv_stream(self.engine, addr)
                attrs.update({k: stats.get(k) for k in
                              ("cached_tokens", "pages", "bytes",
                               "seconds", "complete", "status")})
            else:
                attrs["status"] = "nothing cached"
        except Exception as e:
            attrs["status"] = f"{type(e).__name__}: {e}"
        self._record_kv_migrate_span(t0w, attrs)

    def _record_kv_migrate_span(self, t0w: float, attrs: dict) -> None:
        """One ``llm.kv_migrate`` span per migration (disagg handoff or
        spill pull), chained under the request's trace context."""
        try:
            from ..observability import tracing

            ctx = tracing.current()
            tracing.record_span(tracing.make_span(
                "llm.kv_migrate", "llm", t0w, time.time(),
                ctx.trace_id if ctx else tracing.new_trace_id(),
                ctx.span_id if ctx else "", attrs=attrs))
        except Exception:
            pass

    def _sse_completion_stream(self, body: dict, prompt: str, cid: str,
                               created: int, chat: bool):
        """SSE generator: one ``data:`` event per token, ``[DONE]`` last
        (OpenAI stream framing; flows through Serve's streaming path to the
        proxy as chunked ``text/event-stream``)."""
        model = body.get("model", self.model_id)
        max_tokens = int(body.get("max_tokens", 16))
        temperature = float(body.get("temperature", 0.0))
        obj = "chat.completion.chunk" if chat else "text_completion"
        ids = self.tokenizer.encode(prompt)
        rid = self._next_rid()
        req = Request(rid, ids, max_tokens, temperature,
                      eos_id=self.tokenizer.eos_id,
                      model=self._adapter_for(body.get("model")),
                      deadline=self._effective_deadline(body))
        group = self._group_of(prompt, body.get("session_id"))
        tenant = self._tenant_for(body.get("model"))

        def gen():
            self._maybe_spill_migrate(prompt, body.get("model"))
            # Admit BEFORE the response head: a bounded-queue shed, a
            # quota-exhausted 429, or an invalid prompt surfaces on a
            # clean error status instead of a truncated 200 stream.
            q = self._admit_streaming(req, tenant)
            yield {"__serve_response__": True, "content_type": "text/event-stream"}
            if chat:
                head = {"id": cid, "object": obj, "created": created, "model": model,
                        "choices": [{"index": 0, "delta": {"role": "assistant"},
                                     "finish_reason": None}]}
                yield f"data: {json.dumps(head)}\n\n"
            for event in self._stream_tokens(req, group, q=q,
                                             tenant=tenant):
                # Terminal-only events (deadline expiry) carry token -1:
                # no text, just the finish_reason.
                text = (self.tokenizer.decode([event["token"]])
                        if event["token"] >= 0 else "")
                if chat:
                    choice = {"index": 0, "delta": {"content": text},
                              "finish_reason": _openai_finish(event["finish_reason"]) if event["done"] else None}
                else:
                    choice = {"index": 0, "text": text, "logprobs": None,
                              "finish_reason": _openai_finish(event["finish_reason"]) if event["done"] else None}
                chunk = {"id": cid, "object": obj, "created": created,
                         "model": model, "choices": [choice]}
                yield f"data: {json.dumps(chunk)}\n\n"
            yield "data: [DONE]\n\n"

        return gen()

    def models(self) -> dict:
        return {"object": "list", "data": [{
            "id": self.model_id, "object": "model", "created": 0,
            "owned_by": "ray_tpu",
        }]}

    def engine_metrics(self) -> dict:
        return {**self.engine.metrics,
                "prefix_cache_hit_rate": self.engine.prefix_cache_hit_rate,
                "prefill_suffix_frac": self.engine.prefill_suffix_frac,
                "mixed_dispatch_enabled": self.engine.mixed_dispatch_enabled,
                "speculation_enabled": self.engine.speculation_enabled,
                "spec_accept_rate": self.engine.spec_accept_rate,
                "spec_tokens_per_dispatch":
                    self.engine.spec_tokens_per_dispatch,
                "role": self._role,
                "supports_kv_migration": self.engine.supports_kv_migration}

    def overload_stats(self) -> dict:
        """Engine-side overload counters, picked up by the replica
        actor's ``latency_snapshot`` probe (``serve_overload`` row) and
        folded into ``serve.status()`` per deployment."""
        m = self.engine.metrics
        return {"deadline_expired_queued": m["deadline_expired_queued"],
                "deadline_expired_running": m["deadline_expired_running"],
                "queue_rejects": m["queue_rejects"],
                "admission_rejects": m["admission_rejects"]}

    def tenancy_stats(self) -> dict:
        """Per-tenant rows + adapter residency for this replica, picked
        up by the replica actor's ``latency_snapshot`` probe
        (``serve_tenancy`` row) and folded into ``serve.status()`` /
        ``cli serve status`` per-tenant tables."""
        out: dict = {"tenants": self.tenancy.snapshot(),
                     "adapter_defers":
                         self.engine.metrics.get("adapter_defers", 0),
                     # Most recent flight-recorder breach dumps (deadline
                     # expiries / sheds / TTFT-SLO breaches) on this
                     # replica — the serve.status() "last breach" rows.
                     "last_breaches": self.engine.breach_samples()}
        lm = self.engine.lora_manager
        if lm is not None:
            out["adapters"] = lm.stats()
            out["resident_adapters"] = list(lm.resident())
        return out

    def pool_stats(self) -> dict:
        """Engine page-pool accounting (chaos invariant surface)."""
        return self.engine.pool_stats()

    # ------------------------------------------------------ fleet lifecycle
    def fleet_stats(self) -> dict:
        """Per-replica fleet row, picked up by the replica actor's
        ``latency_snapshot`` probe (``serve_fleet``) and folded by the
        controller into the scale-to-zero / standby decisions: how long
        since the last request landed here, and where the weights are."""
        eng = self.engine
        with self._lock:
            last = self._last_request_ts
        idle = 0.0 if eng.has_work else max(0.0, time.time() - last)
        return {"idle_s": round(idle, 3),
                "residency_capable": eng.supports_weight_residency,
                "weights_on_host": not eng.weights_resident(),
                "weights_demoted": eng.metrics.get("weights_demoted", 0),
                "weights_promoted": eng.metrics.get("weights_promoted", 0),
                "weight_promote_ms":
                    eng.metrics.get("weight_promote_ms", 0.0)}

    def fleet_demote(self) -> dict:
        """Standby demotion: weights to host RAM + idle-adapter unload.
        Refused (``ok=False, reason="busy"``) while requests are in
        flight — the controller just retries next reconcile round."""
        return self.engine.demote_weights_to_host()

    def fleet_promote(self, weight_address: str | None = None) -> dict:
        """Promotion ladder: broadcast stream (when the controller hands
        us a donor's ``weight_address``) → host-RAM copy → deterministic
        cold re-init. Each rung degrades to the next, so a donor dying
        mid-stream costs the faster path, never the promotion."""
        eng = self.engine
        t0 = time.monotonic()
        ladder = []
        if weight_address and eng.supports_weight_residency:
            from .weights import receive_weight_stream

            res = receive_weight_stream(weight_address,
                                        like=eng._host_params)
            if res["params"] is not None:
                out = eng.install_weights(res["params"])
                if out.get("ok"):
                    return {"ok": True, "path": "stream",
                            "bytes": res["bytes"],
                            "seconds": round(time.monotonic() - t0, 6)}
            ladder.append(f"stream:{res['status']}")
        out = eng.promote_weights_from_host()
        if out.get("ok"):
            path = "resident" if out.get("already") else "host"
            return {"ok": True, "path": path, "ladder": ladder,
                    "seconds": round(time.monotonic() - t0, 6)}
        ladder.append(f"host:{out.get('reason', '?')}")
        if eng.supports_weight_residency and not eng.weights_resident():
            # Last resort: weights here come from the seeded init, so a
            # cold re-init reproduces them bit-for-bit (the checkpoint
            # re-load of a real deployment).
            import jax

            from ..models.llama import init_params

            params = init_params(eng.config, jax.random.PRNGKey(self._seed))
            out = eng.install_weights(params)
            if out.get("ok"):
                return {"ok": True, "path": "cold_init", "ladder": ladder,
                        "seconds": round(time.monotonic() - t0, 6)}
            ladder.append(f"cold_init:{out.get('reason', '?')}")
        return {"ok": eng.weights_resident(), "path": "failed",
                "ladder": ladder,
                "seconds": round(time.monotonic() - t0, 6)}

    def open_weight_stream(self, n_readers: int = 1,
                           _die_after_chunks: int | None = None
                           ) -> dict | None:
        """Open a weight broadcast from this (warm or standby) replica:
        N cold/standby replicas stream ONE read of the weights instead
        of N independent loads. Rides the same source-reaping registry
        as the KV spill exporters. Returns ``{"weight_address",
        "fingerprint"}`` or None when there is nothing to serve."""
        eng = self.engine
        params = getattr(eng.executor, "params", None)
        if params is None:
            params = eng._host_params
        if params is None or not eng.supports_weight_residency:
            return None
        from .weights import WeightBroadcastSource

        src = WeightBroadcastSource(
            params, model=self.model_id, n_readers=n_readers,
            _die_after_chunks=_die_after_chunks)
        self._track_spill_source(src)
        return {"weight_address": src.address,
                "fingerprint": src.fingerprint}

    # ---------------------------------------------------------- HTTP entry
    def __call__(self, request):
        """HTTP ingress: OpenAI routes + the legacy ?prompt= GET."""
        path = request.path
        if path.endswith("/v1/models"):
            return self.models()
        try:
            if path.endswith("/v1/completions"):
                return self.completions(request.json())
            if path.endswith("/v1/chat/completions"):
                return self.chat_completions(request.json())
        except ValueError as e:
            # Invalid request (e.g. prompt >= max_len): OpenAI-style error
            # body instead of a bare 500.
            return {"error": {"message": str(e), "type": "invalid_request_error",
                              "code": 400}}
        q = request.query_params
        return self.generate(
            q.get("prompt", ""),
            max_new_tokens=int(q.get("max_new_tokens", 16)),
            temperature=float(q.get("temperature", 0.0)),
        )


def _openai_finish(reason: str) -> str:
    return {"stop": "stop", "length": "length", "max_len": "length",
            "timeout": "length", "cancelled": "stop"}.get(reason, reason or "stop")


def _render_chat(messages: list) -> str:
    """Minimal chat template (byte tokenizer has no special tokens)."""
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages]
    parts.append("assistant:")
    return "\n".join(parts)


def build_llm_app(preset: str = "debug-128", *, num_replicas: int = 1,
                  max_slots: int = 8, max_len: int = 256,
                  page_size: int = 16, prefill_chunk_size: int = 64,
                  decode_steps_per_dispatch: int = 8, tensor_parallel: int = 1,
                  pipeline_parallel: int = 1,
                  num_hosts: int = 1, shard_resources: dict | None = None,
                  shard_runtime_env: dict | None = None,
                  topology: str | None = None,
                  max_ongoing_requests: int = 32, model_id: str | None = None,
                  ray_actor_options: dict | None = None,
                  attention_impl: str = "auto",
                  autoscaling_config=None,
                  prefill_token_budget: int | None = None,
                  max_prefill_seqs_per_step: int = 2,
                  decode_starvation_limit: int = 8,
                  use_compiled_loop: bool | None = None,
                  serve_disaggregation: str | None = None,
                  prefill_replicas: int = 1,
                  host_kv_cache_pages: int = 0,
                  max_queued_requests: int = 0,
                  admission_watermark_pages: int | None = None,
                  speculation_config=None,
                  lora_config: dict | None = None,
                  tenancy_config: dict | None = None):
    """Build a Serve Application serving ``preset`` (serve.run-able).
    Pass ``ray_actor_options={"resources": {"TPU": 1}, ...}`` to pin each
    replica (engine) to a TPU chip. For an engine that SPANS hosts, set
    ``num_hosts`` > 1 with per-host ``shard_resources`` (e.g.
    ``{"TPU": 4, "CPU": 1}``) and optionally ``topology`` (slice type,
    claims the slice-head resource) — the replica then schedules requests
    while per-host shard actors execute the model SPMD over the joint
    mesh (reference vllm_models.py:117-168).

    ``serve_disaggregation="prefill_decode"`` builds the DistServe-style
    split instead of one replica pool: ``prefill_replicas`` ingress
    replicas ("llm-prefill" pool) chunk-prefill prompts and live-migrate
    the KV pages to the ``num_replicas`` decode replicas ("llm-decode"
    pool), which own all token streaming — TTFT-bound and ITL-bound work
    never compete for a replica, and an affinity spill inside either
    pool migrates pages instead of recomputing them."""
    from ..serve import deployment

    engine_kwargs = dict(
        model_id=model_id, max_slots=max_slots, max_len=max_len,
        page_size=page_size, prefill_chunk_size=prefill_chunk_size,
        decode_steps_per_dispatch=decode_steps_per_dispatch,
        tensor_parallel=tensor_parallel,
        pipeline_parallel=pipeline_parallel, num_hosts=num_hosts,
        shard_resources=shard_resources,
        shard_runtime_env=shard_runtime_env, topology=topology,
        attention_impl=attention_impl,
        prefill_token_budget=prefill_token_budget,
        max_prefill_seqs_per_step=max_prefill_seqs_per_step,
        decode_starvation_limit=decode_starvation_limit,
        use_compiled_loop=use_compiled_loop,
        host_kv_cache_pages=host_kv_cache_pages,
        max_queued_requests=max_queued_requests,
        admission_watermark_pages=admission_watermark_pages,
        speculation_config=speculation_config,
        lora_config=lora_config,
        tenancy_config=tenancy_config)
    if serve_disaggregation is None:
        dep = deployment(
            LLMDeployment,
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
        )
        return dep.bind(preset, **engine_kwargs)
    if serve_disaggregation != "prefill_decode":
        raise ValueError(
            f"unknown serve_disaggregation {serve_disaggregation!r} "
            "(use 'prefill_decode' or None)")
    decode_app = deployment(
        LLMDeployment,
        name="llm-decode",
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        autoscaling_config=autoscaling_config,
        ray_actor_options=ray_actor_options,
        pool="decode",
    ).bind(preset, role="decode", **engine_kwargs)
    return deployment(
        LLMDeployment,
        name="llm-prefill",
        num_replicas=max(1, prefill_replicas),
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=ray_actor_options,
        pool="prefill",
    ).bind(preset, role="prefill", decode_handle=decode_app,
           **engine_kwargs)
