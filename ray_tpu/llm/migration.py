"""Live KV-page migration between engines over a loop channel.

The transfer half of disaggregated prefill/decode serving (the
object-manager idea from PAPER.md §1 layer 4 applied to the KV cache):
large immutable buffers — here, prefix-cache pages — MOVE between nodes
instead of being recomputed. A prefill replica streams its request's
pages over a credit-based ``TcpLoopServer`` (``dag/channel.py``) WHILE
later chunks are still prefilling, and the decode replica imports each
chunk as it arrives — so by the time the prompt finishes prefilling,
most of its KV already sits in the decode replica's pool and handoff
latency hides behind prefill compute.

Wire protocol (pickled dicts, exactly-once, in order):

    {"kind": "meta",  "page_size", "model", "prompt_len"}
    {"kind": "pages", "tokens": [...], "k": np, "v": np}   # full blocks
    {"kind": "tail",  "tokens": [...], "k": np, "v": np}   # partial tail
    {"kind": "end"}                                        # complete
    {"kind": "abort"}                                      # source failed

Failure is graceful by construction: chunks arrive in chain order, so a
source death / timeout / reservation failure mid-stream leaves the
importer holding a contiguous PREFIX of the chain — a prefix of a valid
chain is itself a valid chain, so it registers what it has and the
request cold-prefills only the rest.

The same wire shape (meta → payload chunks → end/abort over a
credit-based ``TcpLoopServer``) carries WEIGHT pytrees for the
always-warm fleet: ``llm/weights.py`` is the weight-broadcast analogue
of this module, with N promoting replicas as readers of one warm donor.
"""

from __future__ import annotations

import pickle
import threading
import time

import numpy as np

from ..dag.channel import ChannelClosed, TcpLoopReader, TcpLoopServer


def _config():
    from ..core.config import get_config

    return get_config()


class KVMigrationSource:
    """Prefill-side exporter: streams one (possibly still prefilling)
    request's prefix pages as they complete — or, in STATIC mode
    (:meth:`for_cached_prefix`), a prompt's already-cached trie pages,
    which is how an affinity spill's target pulls the group's hot KV:
    the same chunked wire as the disaggregation handoff, so a slow or
    dying source degrades to the received prefix identically.

    A live request must be admitted with ``pin_for_export=True`` so its
    pages survive retire until the transfer finishes; static plans pin
    their pages via ``engine.pin_prefix_for_export``. Pages exported
    while a request is live are additionally pinned around each
    device→host pull. One background thread per migration; the server
    socket closes via :meth:`close` once the consumer is done (or on
    garbage collection of the socket)."""

    def __init__(self, engine, request, chunk_pages: int | None = None,
                 advertise: str | None = None,
                 _die_after_chunks: int | None = None,
                 static_plan: dict | None = None):
        if static_plan is None:
            assert request.pin_for_export, \
                "migration sources require pin_for_export=True requests"
        self.engine = engine
        self.request = request
        self._static_plan = static_plan
        self.chunk_pages = max(1, chunk_pages
                               or _config().kv_migration_chunk_pages)
        self._server = TcpLoopServer(n_slots=8, n_readers=1,
                                     advertise=advertise)
        # Test/chaos hook: hard-kill the channel after N chunks, as a
        # dead prefill replica would.
        self._die_after = _die_after_chunks
        self._killed = False
        self.stats = {"pages": 0, "bytes": 0, "chunks": 0}
        self._thread = threading.Thread(
            target=self._run_static if static_plan is not None else self._run,
            daemon=True, name="kv-migration-src")
        self._thread.start()

    @classmethod
    def for_cached_prefix(cls, engine, prompt_ids, model: str | None = None,
                          chunk_pages: int | None = None,
                          advertise: str | None = None,
                          _die_after_chunks: int | None = None
                          ) -> "KVMigrationSource | None":
        """Open a migration stream over the engine's CACHED pages
        covering ``prompt_ids``'s longest prefix (the spill-migration
        export). Returns None when nothing is cached — the caller just
        cold-prefills."""
        plan = engine.pin_prefix_for_export(prompt_ids, model)
        if plan is None:
            return None
        return cls(engine, None, chunk_pages=chunk_pages,
                   advertise=advertise, _die_after_chunks=_die_after_chunks,
                   static_plan=plan)

    @property
    def address(self) -> str:
        return self._server.address

    def _send(self, msg: dict) -> None:
        blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        self._server.write(blob, timeout=_config().kv_migration_timeout_s)
        self.stats["bytes"] += len(blob)

    def _export_pinned(self, page_ids: list[int]) -> dict:
        """Pull pages with a transient extra pin: a live request's own
        refcount usually covers them, but a cancel can retire mid-pull."""
        eng = self.engine
        with eng._lock:
            for pid in page_ids:
                eng.allocator.share(pid)
        try:
            return eng.executor.export_pages(page_ids)
        finally:
            with eng._lock:
                for pid in page_ids:
                    eng.allocator.release(pid)

    def _run(self) -> None:
        eng, r = self.engine, self.request
        ps = eng.page_size
        # The last prompt token's hidden state is always recomputed on
        # the importer (it seeds sampling), so cap full blocks exactly
        # like admission matching does.
        cap_full = (len(r.prompt) - 1) // ps
        sent = 0
        try:
            self._send({"kind": "meta", "page_size": ps,
                        "model": r.model or "",
                        "prompt_len": len(r.prompt)})
            while True:
                with eng._lock:
                    done, reason = r.done, r.finish_reason
                    pos = r.prefill_pos
                    table = list(r.block_table) or list(r.export_pinned)
                avail = min(pos // ps, cap_full)
                while sent < avail:
                    hi = min(sent + self.chunk_pages, avail)
                    data = self._export_pinned(table[sent:hi])
                    self._send({"kind": "pages",
                                "tokens": [int(t) for t in
                                           r.prompt[sent * ps:hi * ps]],
                                "k": data["k"], "v": data["v"]})
                    self.stats["pages"] += hi - sent
                    self.stats["chunks"] += 1
                    sent = hi
                    if self._die_after is not None \
                            and self.stats["chunks"] >= self._die_after:
                        self._killed = True
                        self._server.close()  # simulated source death
                        return
                if done:
                    break
                time.sleep(0.002)
            if reason in ("prefilled", "stop", "length"):
                plen = len(r.prompt) - cap_full * ps  # tail rows, 1..page
                if plen > 0 and len(table) > cap_full:
                    data = self._export_pinned([table[cap_full]])
                    self._send({"kind": "tail",
                                "tokens": [int(t) for t in
                                           r.prompt[cap_full * ps:]],
                                "k": data["k"], "v": data["v"]})
                    self.stats["pages"] += 1
                self._send({"kind": "end"})
                eng.metrics["kv_pages_exported"] += self.stats["pages"]
                eng.metrics["kv_migrations_out"] += 1
            else:  # cancelled / admission_failed: nothing trustworthy
                self._send({"kind": "abort"})
        except Exception:
            try:
                self._send({"kind": "abort"})
            except Exception:
                pass
        finally:
            try:
                # Close-after-drain: queued chunks (and the end marker)
                # still reach the reader, then it sees ChannelClosed.
                self._server.close_writer(timeout=5.0)
            except Exception:
                pass
            eng.release_export_pins(r)

    def _run_static(self) -> None:
        """Stream an already-cached prefix (pinned by the plan): full
        trie blocks chunk-by-chunk, then the partial tail, then end —
        the exact wire shape of the live path, so the importer's
        degrade-to-received-prefix semantics are identical."""
        eng, plan = self.engine, self._static_plan
        ps = eng.page_size
        ids = plan["page_ids"]
        full = plan["full_pages"]
        tokens = plan["tokens"]
        try:
            self._send({"kind": "meta", "page_size": ps,
                        "model": plan["model"] or "",
                        "prompt_len": len(tokens)})
            sent = 0
            while sent < full:
                hi = min(sent + self.chunk_pages, full)
                data = self._export_pinned(ids[sent:hi])
                self._send({"kind": "pages",
                            "tokens": tokens[sent * ps:hi * ps],
                            "k": data["k"], "v": data["v"]})
                self.stats["pages"] += hi - sent
                self.stats["chunks"] += 1
                sent = hi
                if self._die_after is not None \
                        and self.stats["chunks"] >= self._die_after:
                    self._killed = True
                    self._server.close()  # simulated source death
                    return
            if plan["partial_len"] and len(ids) > full:
                data = self._export_pinned([ids[full]])
                self._send({"kind": "tail",
                            "tokens": tokens[full * ps:],
                            "k": data["k"], "v": data["v"]})
                self.stats["pages"] += 1
                self.stats["chunks"] += 1
            self._send({"kind": "end"})
            eng.metrics["kv_pages_exported"] += self.stats["pages"]
            eng.metrics["kv_migrations_out"] += 1
        except Exception:
            try:
                self._send({"kind": "abort"})
            except Exception:
                pass
        finally:
            try:
                self._server.close_writer(timeout=5.0)
            except Exception:
                pass
            eng.release_export_pages(ids)

    def join(self, timeout: float | None = 30.0) -> None:
        self._thread.join(timeout)

    def close(self) -> None:
        """Release the server socket (after the consumer drained — the
        STOP already queued by the exporter thread)."""
        self._thread.join(timeout=5.0)
        try:
            self._server.close()
        except Exception:
            pass


def receive_kv_stream(engine, address: str, timeout_s: float | None = None,
                      connect_timeout: float = 10.0) -> dict:
    """Decode-side importer: pull a migration stream into ``engine``'s
    pool, chunk by chunk (overlapping the source's still-running
    prefill), then register the received chain so the next admission of
    the same prompt maps it as ordinary prefix hits.

    Degrades, never fails: an incompatible geometry drops the stream, a
    reservation failure or source death mid-stream registers the
    contiguous prefix received so far, and the caller's request simply
    cold-prefills whatever is left. Returns stats:
    ``{"cached_tokens", "pages", "bytes", "seconds", "complete",
    "status"}``."""
    t0 = time.monotonic()
    stats = {"cached_tokens": 0, "pages": 0, "bytes": 0, "seconds": 0.0,
             "complete": False, "status": "ok"}
    if timeout_s is None:
        timeout_s = _config().kv_migration_timeout_s
    page_ids: list[int] = []
    tokens: list[int] = []
    full_pages = 0
    partial_len = 0
    model = ""
    reader = None
    try:
        reader = TcpLoopReader(address, connect_timeout=connect_timeout)
        deadline = time.monotonic() + timeout_s
        while True:
            blob = reader.read(
                timeout=max(0.1, deadline - time.monotonic()))
            stats["bytes"] += len(blob)
            msg = pickle.loads(blob)
            kind = msg.get("kind")
            if kind == "meta":
                if msg.get("page_size") != engine.page_size \
                        or not engine.supports_kv_migration:
                    stats["status"] = "incompatible"
                    break
                model = msg.get("model") or ""
            elif kind in ("pages", "tail"):
                n = int(np.asarray(msg["k"]).shape[1])
                with engine._lock:
                    ids = (engine.allocator.alloc(n)
                           if engine.allocator.available() >= n else None)
                if ids is None:
                    # Pool pressure: keep the prefix already imported,
                    # never evict live sequences' headroom for more.
                    engine.metrics["kv_import_failures"] += 1
                    stats["status"] = "pressure"
                    break
                engine.executor.import_pages(
                    ids, {"k": msg["k"], "v": msg["v"]})
                page_ids.extend(ids)
                tokens.extend(int(t) for t in msg["tokens"])
                stats["pages"] += n
                if kind == "tail":
                    partial_len = len(msg["tokens"])
                else:
                    full_pages += n
            elif kind == "end":
                stats["complete"] = True
                break
            elif kind == "abort":
                stats["status"] = "aborted"
                break
    except (ChannelClosed, TimeoutError, ConnectionError, OSError,
            EOFError, pickle.UnpicklingError) as e:
        stats["status"] = type(e).__name__
    finally:
        if reader is not None:
            reader.close()
    if page_ids:
        stats["cached_tokens"] = engine.register_imported_chain(
            page_ids, tokens, full_pages, partial_len,
            model=model or None)
        engine.metrics["kv_import_bytes"] += stats["bytes"]
    stats["seconds"] = round(time.monotonic() - t0, 6)
    return stats
