"""Multi-LoRA serving: device-resident adapter stacks + per-slot routing.

The reference ships dynamic LoRA adapter loading and multiplexed serving
(``python/ray/llm/_internal/serve/deployments/llm/multiplex/
lora_model_loader.py``; ``configs/server_models.py:141,236`` —
``dynamic_lora_loading_path`` / ``lora_config``) and delegates the
batched multi-adapter compute to vLLM's SGMV/BGMV CUDA kernels. TPU
redesign: adapters live in a fixed device-resident STACK

    A[proj]: [L, max_loras, E_in, r]     B[proj]: [L, max_loras, r, E_out]

for the four attention projections (q/k/v/o). A decode batch carries a
per-slot adapter index; the jitted step gathers each slot's A/B rows and
adds ``(h @ A) @ B`` to the frozen base projection — one compiled
program for every adapter mix, XLA tiling the gathered einsums onto the
MXU (the property vLLM gets from custom CUDA). Index 0 is the identity
adapter (zeros): requests for the base model ride the same program.

Host side, ``LoRAManager`` is the dynamic loader: adapter_id -> stack
slot with LRU eviction; loading an adapter writes its (zero-padded to
``max_rank``) A/B into the stack via one ``jit`` scatter per projection.
Adapters load from ``.npz`` files (``{wq|wk|wv|wo}.{A|B}`` arrays, rank
<= max_rank) through ``pyarrow.fs`` so local paths and ``gs://``-style
URIs both work — the reference's ``dynamic_lora_loading_path``.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

PROJS = ("wq", "wk", "wv", "wo")


@dataclass(frozen=True)
class LoRAServingConfig:
    """Engine-level knob (reference ``LLMConfig.lora_config``)."""

    max_loras: int = 4          # stack slots (excluding the identity slot)
    max_rank: int = 16
    dynamic_lora_loading_path: str | None = None  # base URI for adapters
    # HBM residency cap: at most this many adapters occupy stack slots at
    # once (0 = max_loras). The stack is allocated for max_loras either
    # way; the cap bounds how many the LRU keeps WARM, so a fleet can
    # trade hot-load latency for headroom per replica.
    max_loaded_adapters: int = 0


def init_lora_stack(config, max_loras: int, max_rank: int) -> dict:
    """All-zero adapter stacks (slot 0 = identity, never evicted)."""
    c = config
    n = max_loras + 1
    L, E, H, KH, D = (c.n_layers, c.hidden, c.n_heads, c.n_kv_heads,
                      c.head_dim)
    dims = {"wq": (E, H * D), "wk": (E, KH * D), "wv": (E, KH * D),
            "wo": (H * D, E)}
    stack = {}
    for p, (ein, eout) in dims.items():
        stack[f"{p}.A"] = jnp.zeros((L, n, ein, max_rank), c.dtype)
        stack[f"{p}.B"] = jnp.zeros((L, n, max_rank, eout), c.dtype)
    return stack


def load_adapter_arrays(path: str) -> dict[str, np.ndarray]:
    """Read ``{proj}.{A|B}`` arrays from an ``.npz`` at a pyarrow.fs URI.

    A[proj]: [L, E_in, r], B[proj]: [L, r, E_out] (r <= max_rank).
    """
    import io

    from pyarrow import fs as pafs

    filesystem, fspath = pafs.FileSystem.from_uri(path) if "://" in path \
        else (pafs.LocalFileSystem(), path)
    with filesystem.open_input_stream(fspath) as f:
        data = f.read()
    npz = np.load(io.BytesIO(data))
    return {k: npz[k] for k in npz.files}


@functools.partial(jax.jit, donate_argnames=("stack",))
def _install(stack: dict, slot, arrays: dict) -> dict:
    """Write one adapter's (rank-padded) A/B into stack slot ``slot``."""
    out = dict(stack)
    for k, v in arrays.items():
        out[k] = out[k].at[:, slot].set(v.astype(out[k].dtype))
    return out


def lora_delta(h, A, B, l, idx):
    """Batched per-slot LoRA delta for one projection at layer ``l``.

    h:   [n, S, E_in] activations.
    A:   [L, n_slots_stack, E_in, r]; B: [L, n_slots_stack, r, E_out].
    idx: [n] int32 — each batch row's adapter slot (0 = identity/zeros).
    Returns [n, S, E_out].
    """
    a = A[l, idx]                                  # [n, E_in, r]
    b = B[l, idx]                                  # [n, r, E_out]
    return jnp.einsum("nsr,nro->nso", jnp.einsum("nse,ner->nsr", h, a), b)


def lora_delta_single(h, A, B, l, idx):
    """Single-sequence (prefill) variant: h [1, C, E_in], scalar idx."""
    a = A[l, idx]                                  # [E_in, r]
    b = B[l, idx]
    return jnp.einsum("bcr,ro->bco", jnp.einsum("bce,er->bcr", h, a), b)


class LoRAManager:
    """Host-side dynamic adapter registry: id -> stack slot, LRU evicted.

    Slot 0 is the identity adapter (the base model). ``acquire`` returns
    the slot for an adapter id, loading it into a free/evicted slot on
    first use (reference ``LoraModelLoader.load_model``; disk->HBM here,
    no remote download cache needed — pyarrow.fs reads the URI directly).

    Residency/pin/LRU bookkeeping lives in ``tenancy.AdapterPool``
    (shared with the serve status plumbing); this class owns naming,
    loading, and the device install. When every resident adapter is
    pinned by in-flight requests, ``acquire`` raises
    ``tenancy.AdapterCapacityError`` and the ENGINE defers admission
    (head-of-line wait) instead of failing the request.
    """

    def __init__(self, config, serving: LoRAServingConfig, install_fn):
        """``install_fn(slot, arrays_dict)`` writes into the device stack
        (the executor owns the stack arrays; the manager owns naming)."""
        from .tenancy import AdapterPool

        self._config = config
        self._serving = serving
        self._install = install_fn
        self._pool = AdapterPool(
            capacity=serving.max_loras,
            max_resident=getattr(serving, "max_loaded_adapters", 0))
        # Explicit eviction zeroes the device slot (one install of the
        # identity adapter) so the HBM is reclaimed NOW, not whenever a
        # future load happens to recycle the slot.
        self._pool.on_evict = self._zero_slot

    def resolve_path(self, adapter_id: str) -> str:
        base = self._serving.dynamic_lora_loading_path
        if base is None:
            raise ValueError(
                "lora_config.dynamic_lora_loading_path is not set; cannot "
                f"load adapter {adapter_id!r}")
        return f"{base.rstrip('/')}/{adapter_id}.npz"

    def acquire(self, adapter_id: str | None) -> int:
        """Slot for this request's adapter (0 = base). Pins the slot for
        the request's lifetime; pair with ``release``. A cold adapter
        hot-loads (filesystem read + device scatter) and records an
        ``llm.adapter_load`` span; ``AdapterCapacityError`` propagates
        un-wrapped so admission can defer rather than fail."""
        if not adapter_id:
            return 0
        slot = self._pool.lookup(adapter_id)
        if slot is not None:
            return slot
        slot = self._pool.begin_load(adapter_id)   # may raise capacity
        t0 = time.time()
        try:
            arrays = self._pad(load_adapter_arrays(self.resolve_path(adapter_id)))
            self._install(slot, arrays)
        except Exception:
            self._pool.abort_load(adapter_id)
            raise
        load_ms = (time.time() - t0) * 1000.0
        self._pool.commit_load(adapter_id, load_ms)
        self._record_load_span(adapter_id, slot, t0, load_ms)
        return slot

    def _record_load_span(self, adapter_id: str, slot: int, t0: float,
                          load_ms: float) -> None:
        from ..observability import tracing

        wire = tracing.current_wire()
        tracing.record_span(tracing.make_span(
            "llm.adapter_load", "llm", t0, t0 + load_ms / 1000.0,
            (wire or {}).get("trace_id", ""),
            (wire or {}).get("span_id", ""),
            attrs={"adapter": adapter_id, "slot": slot,
                   "load_ms": round(load_ms, 3)}))

    def release(self, slot: int) -> None:
        if slot == 0:
            return
        self._pool.unpin_slot(slot)

    def _zero_slot(self, adapter_id: str, slot: int) -> None:
        """Write the identity (all-zero) adapter over an evicted slot."""
        c, r_max = self._config, self._serving.max_rank
        L = c.n_layers
        dims = {"wq": (c.hidden, c.n_heads * c.head_dim),
                "wk": (c.hidden, c.n_kv_heads * c.head_dim),
                "wv": (c.hidden, c.n_kv_heads * c.head_dim),
                "wo": (c.n_heads * c.head_dim, c.hidden)}
        zeros = {}
        for p, (ein, eout) in dims.items():
            zeros[f"{p}.A"] = np.zeros((L, ein, r_max), np.float32)
            zeros[f"{p}.B"] = np.zeros((L, r_max, eout), np.float32)
        self._install(slot, zeros)

    def evict(self, adapter_id: str) -> bool:
        """Explicitly unload one idle adapter (device slot zeroed)."""
        return self._pool.evict(adapter_id) is not None

    def unload_idle(self) -> int:
        """Unload every adapter not pinned by an in-flight request —
        the fleet scale-to-zero HBM reclaim. Returns adapters released."""
        return len(self._pool.evict_idle())

    def resident(self) -> dict[str, int]:
        """adapter_id -> stack slot, LRU order (``serve.status()`` rows)."""
        return self._pool.resident()

    def stats(self) -> dict:
        return self._pool.stats()

    def _pad(self, arrays: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        """Zero-pad rank to max_rank and validate shapes."""
        c, r_max = self._config, self._serving.max_rank
        dims = {"wq": (c.hidden, c.n_heads * c.head_dim),
                "wk": (c.hidden, c.n_kv_heads * c.head_dim),
                "wv": (c.hidden, c.n_kv_heads * c.head_dim),
                "wo": (c.n_heads * c.head_dim, c.hidden)}
        out = {}
        for p, (ein, eout) in dims.items():
            a, b = arrays[f"{p}.A"], arrays[f"{p}.B"]
            if a.shape[0] != c.n_layers or a.shape[1] != ein:
                raise ValueError(f"{p}.A shape {a.shape} does not match model")
            r = a.shape[2]
            if r > r_max:
                raise ValueError(f"adapter rank {r} > max_rank {r_max}")
            if b.shape != (c.n_layers, r, eout):
                raise ValueError(f"{p}.B shape {b.shape} does not match model")
            out[f"{p}.A"] = np.pad(a, ((0, 0), (0, 0), (0, r_max - r)))
            out[f"{p}.B"] = np.pad(b, ((0, 0), (0, r_max - r), (0, 0)))
        return out


def save_adapter(path: str, arrays: dict[str, np.ndarray]) -> None:
    """Write an adapter ``.npz`` (test/tooling helper)."""
    import io

    from pyarrow import fs as pafs

    filesystem, fspath = pafs.FileSystem.from_uri(path) if "://" in path \
        else (pafs.LocalFileSystem(), path)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with filesystem.open_output_stream(fspath) as f:
        f.write(buf.getvalue())
