"""Continuous-batching inference engine.

The scheduler half of what the reference delegates to vLLM
(``AsyncLLMEngine`` in
``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py``):
requests arrive at any time, prefill is interleaved with batched decode,
and finished sequences free their slot for waiting requests immediately
(continuous batching, not static batching).

TPU shape discipline: decode always runs the full ``[max_slots]`` batch
(inactive slots compute garbage that is ignored — branchless, so one
compiled program serves every occupancy), and prompts pad to power-of-two
buckets so prefill compiles once per bucket, not once per prompt length.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig, PRESETS, init_params
from .model import decode_step, init_cache, insert_kv, prefill


@dataclass
class Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_id: int | None = None
    # runtime state
    generated: list[int] = field(default_factory=list)
    slot: int = -1
    pos: int = 0  # next position to write
    done: bool = False
    finish_reason: str = ""


class InferenceEngine:
    """Single-host engine; one slot-cache resident on the default device.

    Thread-safety: ``add_request``/``cancel`` may be called from any
    thread; ``step`` must be called from one driver thread (the serving
    replica's engine loop).
    """

    def __init__(
        self,
        config: LlamaConfig | str = "debug",
        params=None,
        *,
        max_slots: int = 8,
        max_len: int = 512,
        seed: int = 0,
    ):
        self.config = PRESETS[config] if isinstance(config, str) else config
        if params is None:
            params = init_params(self.config, jax.random.PRNGKey(seed))
        self.params = params
        self.max_slots = max_slots
        self.max_len = max_len
        self.cache = init_cache(self.config, max_slots, max_len)
        self._free_slots = list(range(max_slots))
        self._active: dict[int, Request] = {}
        self._waiting: deque[Request] = deque()
        self._lock = threading.Lock()
        self._key = jax.random.PRNGKey(seed ^ 0x5EED)
        self._counter = itertools.count()
        # Host-side mirrors of the decode-step inputs.
        self._tokens = np.zeros(max_slots, np.int32)
        self._pos = np.zeros(max_slots, np.int32)
        self.buckets = [b for b in (32, 64, 128, 256, 512, 1024, 2048, 4096) if b <= max_len]

    # ------------------------------------------------------------- admission
    def add_request(self, request: Request) -> None:
        if len(request.prompt) >= self.max_len:
            raise ValueError(
                f"prompt of {len(request.prompt)} tokens >= max_len {self.max_len}"
            )
        with self._lock:
            self._waiting.append(request)

    def cancel(self, request_id: str) -> None:
        with self._lock:
            keep: deque[Request] = deque()
            for r in self._waiting:
                if r.request_id == request_id:
                    r.done, r.finish_reason = True, "cancelled"
                else:
                    keep.append(r)
            self._waiting = keep
            for slot, r in list(self._active.items()):
                if r.request_id == request_id:
                    r.done, r.finish_reason = True, "cancelled"
                    self._retire(slot)

    @property
    def has_work(self) -> bool:
        with self._lock:
            return bool(self._waiting or self._active)

    def _retire(self, slot: int) -> None:
        # Idempotent: cancel() and _emit() can both observe a finished
        # request; the slot must enter the free list exactly once.
        if self._active.pop(slot, None) is not None:
            self._free_slots.append(slot)

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_len

    # ------------------------------------------------------------------ step
    def step(self) -> list[dict]:
        """Advance the engine: admit one waiting request (prefill) if a slot
        is free, else run one batched decode step. Returns emission events
        ``{"request_id", "token", "done", "finish_reason"}``."""
        with self._lock:
            admit = self._waiting.popleft() if self._waiting and self._free_slots else None
        if admit is not None:
            return self._prefill_one(admit)
        if self._active:
            return self._decode_all()
        return []

    def _sample(self, logits: jax.Array, temperature: float) -> int:
        if temperature <= 0.0:
            return int(jnp.argmax(logits))
        self._key, sub = jax.random.split(self._key)
        return int(jax.random.categorical(sub, logits / temperature))

    def _prefill_one(self, r: Request) -> list[dict]:
        bucket = self._bucket(len(r.prompt))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(r.prompt)] = r.prompt
        ks, vs, hidden = prefill(self.params, jnp.asarray(padded), self.config)
        with self._lock:
            slot = self._free_slots.pop()
            r.slot = slot
            self._active[slot] = r
        self.cache = insert_kv(self.cache, ks, vs, jnp.int32(slot), self.config, self.max_len)
        last = hidden[0, len(r.prompt) - 1]
        logits = (last @ self.params["lm_head"]).astype(jnp.float32)
        token = self._sample(logits, r.temperature)
        r.pos = len(r.prompt)
        return [self._emit(r, token)]

    def _decode_all(self) -> list[dict]:
        with self._lock:
            active = dict(self._active)
        if not active:
            return []
        temps = np.ones(self.max_slots, np.float32)
        for slot, r in active.items():
            self._tokens[slot] = r.generated[-1]
            self._pos[slot] = r.pos
            temps[slot] = r.temperature
        logits, self.cache = decode_step(
            self.params, self.cache, jnp.asarray(self._tokens), jnp.asarray(self._pos), self.config
        )
        # One batched sample + one device->host transfer per step (not one
        # per slot): greedy argmax and tempered categorical computed for
        # all slots, picked per-slot by temperature.
        self._key, sub = jax.random.split(self._key)
        greedy = jnp.argmax(logits, axis=-1)
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        sampled = jax.random.categorical(sub, scaled)
        tokens = np.asarray(jnp.where(jnp.asarray(temps) > 0.0, sampled, greedy))
        events = []
        for slot, r in active.items():
            r.pos += 1
            events.append(self._emit(r, int(tokens[slot])))
        return events

    def _emit(self, r: Request, token: int) -> dict:
        r.generated.append(token)
        if r.eos_id is not None and token == r.eos_id:
            r.done, r.finish_reason = True, "stop"
        elif len(r.generated) >= r.max_new_tokens:
            r.done, r.finish_reason = True, "length"
        elif r.pos >= self.max_len - 1:
            r.done, r.finish_reason = True, "max_len"
        if r.done:
            with self._lock:
                self._retire(r.slot)  # idempotent if cancel() beat us to it
        return {
            "request_id": r.request_id,
            "token": token,
            "done": r.done,
            "finish_reason": r.finish_reason,
        }

    # ------------------------------------------------------------ conveniences
    def generate(self, prompt: list[int], max_new_tokens: int = 32,
                 temperature: float = 0.0, eos_id: int | None = None) -> list[int]:
        """Blocking single-prompt helper (tests / offline use)."""
        rid = f"gen-{next(self._counter)}"
        r = Request(rid, list(prompt), max_new_tokens, temperature, eos_id)
        self.add_request(r)
        while not r.done:
            self.step()
        return r.generated
