"""Continuous-batching inference engine over a paged KV cache.

The scheduler half of what the reference delegates to vLLM
(``AsyncLLMEngine`` in ``python/ray/llm/_internal/serve/deployments/llm/
vllm/vllm_engine.py:250``): requests arrive at any time, **chunked
prefill** interleaves with batched decode (bounding TTFT impact on
running streams), finished sequences free their pages immediately, and
hash-matched prompt prefixes reuse previously computed pages without
recomputation — full token blocks AND partial tail blocks, shared
read-only with copy-on-write forking at the first conflicting write
(vLLM/SGLang-style block-level prefix caching).

TPU shape discipline: decode always runs the full ``[max_slots]`` batch
(inactive slots write to private trash pages — branchless, one compiled
program for every occupancy), and prefill chunks are fixed-size buckets so
XLA compiles one program per bucket, not per prompt length.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any

import logging

import numpy as np

from ..models.llama import LlamaConfig, PRESETS
from ..observability import loop_recorder
from .executor import LocalEngineExecutor

logger = logging.getLogger(__name__)


@dataclass
class Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_id: int | None = None
    stop_ids: list[int] = field(default_factory=list)
    # LoRA adapter id (None/"" = base model); resolved to a device stack
    # slot at admission (reference: per-request `model` routing through
    # serve's multiplexed LoRA deployments)
    model: str | None = None
    # runtime state
    generated: list[int] = field(default_factory=list)
    slot: int = -1
    pos: int = 0                 # next position to write
    prefill_pos: int = 0         # prompt tokens already prefilled
    block_table: list[int] = field(default_factory=list)
    done: bool = False
    finish_reason: str = ""
    lora_slot: int = 0
    arrived_at: float = field(default_factory=time.monotonic)
    arrived_wall: float = field(default_factory=time.time)
    first_token_at: float | None = None
    first_token_wall: float | None = None
    cached_prefix_tokens: int = 0
    # Prefix sharing state: the first `shared_pages` block-table entries
    # are refcounted read-only cache pages; `cow_page` is the page
    # reserved at admission to receive the COW fork of a shared partial
    # tail block the suffix will write into (None once forked/unused).
    shared_pages: int = 0
    partial_len: int = 0
    cow_page: int | None = None
    # Disaggregated serving: a prefill-pool request computes its prompt's
    # KV and retires WITHOUT sampling (finish_reason "prefilled") — the
    # pages enter the prefix trie and ship to a decode replica instead.
    # ``pin_for_export`` keeps the retired pages refcounted until the
    # migration exporter releases them (``release_export_pins``), so
    # pool pressure can never recycle a page mid-transfer.
    prefill_only: bool = False
    pin_for_export: bool = False
    export_pinned: list[int] = field(default_factory=list)
    # End-to-end deadline (absolute wall clock, ``time.time()`` scale),
    # threaded from the serve proxy: a request that expires while still
    # WAITING fails fast without ever touching the engine; one that
    # expires mid-prefill/mid-decode is aborted and its pages freed the
    # same tick. None = never expires.
    deadline: float | None = None
    # Trace context ({"trace_id", "span_id"}) captured from the submitting
    # thread at add_request: the engine loop runs detached, so prefill/
    # decode spans parent onto this instead of any thread-local state.
    trace: dict | None = None
    # Speculative-decoding accounting (drafted tokens verified for this
    # request, drafts accepted, verify rounds that rolled a draft back)
    # — the per-request view behind the llm.speculate span.
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_rollbacks: int = 0
    # Flight recorder (observability/loop_recorder.py): a bounded,
    # always-on event timeline — admission, prefix hits, COW forks,
    # prefill chunks, first token, per-token ITL, speculation rounds,
    # migrations, shed/deadline, retire. On SLO breach it dumps ONCE as
    # a ``llm.request_timeline`` span (see ``InferenceEngine.
    # dump_timeline``).
    timeline: "object" = None

    def __post_init__(self):
        if self.timeline is None:
            from ..observability.loop_recorder import RequestTimeline

            self.timeline = RequestTimeline()


class QueueFullError(RuntimeError):
    """The engine's bounded admission queue (``max_queued_requests``)
    refused the request — overload protection's per-replica backpressure.
    Carries the HTTP shape the serve proxy answers with (503 +
    Retry-After) so the shed is honest and fast."""

    http_status = "503 Service Unavailable"
    reason = "replica_queue_full"

    def __init__(self, message: str, retry_after: int = 1):
        super().__init__(message)
        self.retry_after = max(1, int(retry_after))


class PageAllocator:
    """Page pool bookkeeping: free list, per-page refcounts, and a prefix
    TRIE keyed on token-block chain hashes (pages are immutable once
    cached, so a page whose chain matches can be shared read-only between
    sequences — the reference's automatic prefix caching, block-level as
    in vLLM/SGLang).

    The trie has two kinds of entries:

      * **full-block nodes** (``prefix_map``: chain hash -> page id) with
        parent/children edges, matched block-by-block by
        ``match_prefix``;
      * **partial tail blocks** (``_partials``: the raw token tuple of a
        sequence's last, partially-filled page, keyed under its parent
        node) matched by longest-common-prefix on ``match_partial`` — the
        reader maps the page read-only and COW-forks it (``fork``) before
        its first write lands mid-page.

    Eviction is LRU over refcount-0 cached pages only (shared-page pins
    always survive pressure), preferring LEAF entries so interior chain
    nodes outlive their extensions; evicting an interior node unlinks its
    now-unreachable cached descendants back to the free list.
    """

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self.free: list[int] = list(range(num_pages))
        self.refcount: dict[int, int] = {}
        # chain-hash of tokens[0:(i+1)*page] -> page_id, + LRU stamps for
        # eviction of refcount-0 cached pages.
        self.prefix_map: dict[bytes, int] = {}
        self.page_hash: dict[int, bytes] = {}
        self.last_used: dict[int, float] = {}
        # Trie edges over chain hashes (a chain hash IS a path identity,
        # so nodes are keyed by it directly; parents may be virtual —
        # the adapter-scoped root hash has no page).
        self._children: dict[bytes, set[bytes]] = {}
        self._parent: dict[bytes, bytes] = {}
        # Partial tail blocks: parent chain hash -> {token tuple: page_id}
        self._partials: dict[bytes, dict[tuple, int]] = {}
        self._partial_pages: dict[int, tuple[bytes, tuple]] = {}
        # Tiered-KV hook: called as ``on_evict(page_id, chain_hash)`` for
        # every cached FULL-block page about to be recycled (the victim
        # and its unreachable cached descendants), BEFORE its data is
        # reused — the engine spills the page to host RAM keyed by its
        # chain hash so a future match_prefix can restore it.
        self.on_evict = None

    def available(self) -> int:
        return len(self.free) + sum(
            1 for p in self.page_hash if self.refcount.get(p, 0) == 0
        ) + sum(
            1 for p in self._partial_pages if self.refcount.get(p, 0) == 0
        )

    def alloc(self, n: int) -> list[int] | None:
        if self.available() < n:
            return None
        out = []
        for _ in range(n):
            if self.free:
                pid = self.free.pop()
            else:
                pid = self._evict_one()
            self.refcount[pid] = 1
            out.append(pid)
        return out

    def fork(self, page_id: int) -> int | None:
        """COW fork: allocate a fresh page to receive a copy of shared
        ``page_id`` (the caller device-copies the rows and swaps its own
        table entry). Exactly one page — the shared original keeps its
        refcount and cache entries untouched for its other readers."""
        got = self.alloc(1)
        return got[0] if got is not None else None

    def _unlink(self, page_id: int) -> None:
        """Drop every cache entry for ``page_id`` (full-block node edges
        or partial-tail entry). The page itself is NOT freed."""
        h = self.page_hash.pop(page_id, None)
        if h is not None:
            self.prefix_map.pop(h, None)
            parent = self._parent.pop(h, None)
            if parent is not None and parent in self._children:
                self._children[parent].discard(h)
                if not self._children[parent]:
                    del self._children[parent]
        entry = self._partial_pages.pop(page_id, None)
        if entry is not None:
            parent, key = entry
            sub = self._partials.get(parent)
            if sub is not None:
                sub.pop(key, None)
                if not sub:
                    del self._partials[parent]

    def _evict_one(self) -> int:
        """LRU victim among refcount-0 cached pages, leaf entries first.
        Evicting an interior chain node also unlinks its (unreachable)
        cached descendants back to the free list."""
        best = None
        for h, p in self.prefix_map.items():
            if self.refcount.get(p, 0):
                continue
            leaf = 0 if (h not in self._children
                         and h not in self._partials) else 1
            key = (leaf, self.last_used.get(p, 0.0))
            if best is None or key < best[0]:
                best = (key, p, h)
        for p in self._partial_pages:
            if self.refcount.get(p, 0):
                continue
            key = (0, self.last_used.get(p, 0.0))
            if best is None or key < best[0]:
                best = (key, p, None)
        _, victim, victim_hash = best
        if self.on_evict is not None and victim_hash is not None:
            # Spill BEFORE unlink/reuse: the page still holds valid K/V.
            self.on_evict(victim, victim_hash)
        descendants = []
        if victim_hash is not None and victim_hash in self._children:
            stack = [victim_hash]
            while stack:
                h = stack.pop()
                stack.extend(self._children.pop(h, ()))
                for key, p in self._partials.pop(h, {}).items():
                    descendants.append(p)
                    self._partial_pages.pop(p, None)
                if h != victim_hash:
                    p = self.prefix_map.pop(h, None)
                    self._parent.pop(h, None)
                    if p is not None:
                        if self.on_evict is not None:
                            self.on_evict(p, h)
                        self.page_hash.pop(p, None)
                        descendants.append(p)
        self._unlink(victim)
        for p in descendants:
            # Unreachable now; cached refcount-0 descendants go straight
            # back to the pool, pinned ones free on their final release.
            if not self.refcount.get(p, 0) and p != victim \
                    and p not in self.free:
                self.free.append(p)
        return victim

    def share(self, page_id: int) -> None:
        self.refcount[page_id] = self.refcount.get(page_id, 0) + 1
        self.last_used[page_id] = time.monotonic()

    def release(self, page_id: int) -> None:
        count = self.refcount.get(page_id, 1) - 1
        self.refcount[page_id] = count
        if count <= 0:
            self.refcount.pop(page_id, None)
            if page_id in self.page_hash or page_id in self._partial_pages:
                self.last_used[page_id] = time.monotonic()  # evictable, cached
            else:
                self.free.append(page_id)

    def register_prefix(self, page_id: int, chain_hash: bytes,
                        parent_hash: bytes = b"") -> None:
        if chain_hash in self.prefix_map or page_id in self.page_hash \
                or page_id in self._partial_pages:
            return
        self.prefix_map[chain_hash] = page_id
        self.page_hash[page_id] = chain_hash
        self.last_used[page_id] = time.monotonic()
        self._parent[chain_hash] = parent_hash
        self._children.setdefault(parent_hash, set()).add(chain_hash)

    def register_partial(self, parent_hash: bytes, tokens: tuple,
                         page_id: int) -> None:
        """Cache a sequence's partially-filled tail page: ``tokens`` are
        the page's valid rows, stored raw so match_partial can take a
        shorter common prefix than the producer wrote."""
        if not tokens or page_id in self.page_hash \
                or page_id in self._partial_pages:
            return
        sub = self._partials.setdefault(parent_hash, {})
        if tokens in sub:
            return
        sub[tokens] = page_id
        self._partial_pages[page_id] = (parent_hash, tokens)
        self.last_used[page_id] = time.monotonic()

    def lookup_prefix(self, chain_hash: bytes) -> int | None:
        return self.prefix_map.get(chain_hash)

    def match_prefix(self, chain_hashes: list[bytes]) -> list[int]:
        """Longest cached chain: one page per matched full block, in
        order, stopping at the first miss."""
        hits: list[int] = []
        for h in chain_hashes:
            pid = self.prefix_map.get(h)
            if pid is None:
                break
            hits.append(pid)
        return hits

    def match_partial(self, parent_hash: bytes, tokens: tuple,
                      cap: int) -> tuple[int, int] | None:
        """Best partial tail-block under ``parent_hash``: the entry with
        the longest common prefix against ``tokens``, capped at ``cap``
        rows (the caller caps so at least one prompt token is always
        computed). Returns ``(page_id, matched_len)`` or None."""
        best = None
        for entry, pid in self._partials.get(parent_hash, {}).items():
            n = 0
            for a, b in zip(entry, tokens):
                if a != b:
                    break
                n += 1
            n = min(n, cap)
            if n > 0 and (best is None or n > best[1]):
                best = (pid, n)
        return best


class InferenceEngine:
    """Paged-KV engine: this class is the host-side SCHEDULER (slots,
    pages, prefix cache, admission); every device interaction goes through
    an executor — ``LocalEngineExecutor`` for this process's devices
    (optionally a tp mesh), or a multi-host fan-out (``multihost.py``).
    ``add_request``/``cancel`` are thread-safe; ``step`` must be called
    from one driver thread (the serving replica's engine loop)."""

    def __init__(
        self,
        config: LlamaConfig | str = "debug",
        params=None,
        *,
        max_slots: int = 8,
        max_len: int = 512,
        page_size: int = 16,
        num_pages: int | None = None,
        prefill_chunk_size: int = 128,
        decode_steps_per_dispatch: int = 8,
        enable_prefix_cache: bool = True,
        mesh=None,
        executor=None,
        seed: int = 0,
        attention_impl: str = "auto",
        lora_config=None,
        prefill_token_budget: int | None = None,
        max_prefill_seqs_per_step: int = 2,
        decode_starvation_limit: int = 8,
        host_kv_cache_pages: int = 0,
        max_queued_requests: int = 0,
        admission_watermark_pages: int | None = None,
        speculation_config=None,
    ):
        self.config = PRESETS[config] if isinstance(config, str) else config
        self.mesh = mesh
        self.max_slots = max_slots
        self.page_size = page_size
        assert max_len % page_size == 0, "max_len must be a multiple of page_size"
        self.max_len = max_len
        self.max_pages_per_seq = max_len // page_size
        self.prefill_chunk_size = min(prefill_chunk_size, max_len)
        assert self.prefill_chunk_size % page_size == 0
        self.enable_prefix_cache = enable_prefix_cache
        # Decode steps fused into one device dispatch (lax.scan): a host
        # sync costs a full round trip (~150ms over a remote-dispatch
        # tunnel), so syncing once per K tokens is the difference between
        # 7 tok/s/slot and wire-speed decode.
        self.decode_steps_per_dispatch = max(1, decode_steps_per_dispatch)
        # Token-budget mixed dispatch (Sarathi/vLLM chunked-prefill
        # scheduling): each step carries the full decode batch PLUS up to
        # `prefill_token_budget` prompt tokens (≤ `max_prefill_seqs_per_step`
        # distinct prompts) in ONE fused dispatch, so a long prompt no
        # longer head-of-line-blocks running streams. Default budget = one
        # prefill chunk per step; 0 = legacy strict prefill-first
        # schedule. `decode_starvation_limit` guards the FALLBACK path
        # (pp meshes, LoRA stacks — no fused entry point): after that many
        # consecutive prefill-only steps with live decoders, one decode
        # burst is forced (0 disables the guard).
        if prefill_token_budget is None:
            prefill_token_budget = self.prefill_chunk_size
        self.prefill_token_budget = (
            max(page_size, prefill_token_budget) if prefill_token_budget else 0)
        self.max_prefill_seqs_per_step = max(1, max_prefill_seqs_per_step)
        self.decode_starvation_limit = max(0, decode_starvation_limit)
        self._starved_steps = 0
        self.num_pages = self.total_pages(max_slots, max_len, page_size, num_pages)
        if executor is None:
            executor = LocalEngineExecutor(
                self.config, params, max_slots=max_slots,
                num_pages=self.num_pages, page_size=page_size, mesh=mesh,
                seed=seed, attention_impl=attention_impl,
                lora_config=lora_config,
            )
        self.executor = executor
        # Resolved decode path ("paged" = v2 staging-buffer kernel: pool
        # read-only per K-step dispatch, one commit scatter at the
        # dispatch boundary; "dense" = bucketed gather). "auto" resolves
        # per backend/mesh in executor.resolve_attention_impl.
        self.attention_impl = getattr(executor, "attention_impl", "dense")
        # Speculative decoding (ROADMAP 5): a host-side drafter proposes
        # K tokens per active slot each decode tick and ONE verify
        # dispatch scores all K+1 positions (model.verify_block). None =
        # plain decode, bit-for-bit the pre-speculation path.
        from .speculative import SpeculationConfig

        self.speculation = SpeculationConfig.normalize(speculation_config)
        self._drafter = (self.speculation.build_drafter()
                         if self.speculation is not None else None)
        self.lora_manager = None
        if lora_config is not None:
            from .lora import LoRAManager

            self.lora_manager = LoRAManager(
                self.config, lora_config, executor.install_adapter)
        self._lora_idx = np.zeros(max_slots, np.int32)
        self.allocator = PageAllocator(self.num_pages)
        # Trash pages 0..max_slots-1 are permanently owned by their slot.
        for s in range(max_slots):
            self.allocator.free.remove(s)
        self._free_slots = list(range(max_slots))
        # Overload protection: bound on requests WAITING for admission
        # (0 = unbounded) — over it add_request sheds with QueueFullError
        # instead of letting the queue (and every waiter's TTFT) grow
        # without limit; and the admission watermark — extra free-page
        # headroom admission preserves on top of each request's
        # worst-case reservation (admission reserves prompt+max_tokens
        # growth up front, so a RUNNING slot can never hit a mid-decode
        # allocation failure; the watermark additionally keeps headroom
        # for in-flight KV imports/migrations).
        self.max_queued_requests = max(0, max_queued_requests)
        if admission_watermark_pages is None:
            from ..core.config import get_config

            admission_watermark_pages = \
                get_config().serve_admission_watermark_pages
        self.admission_watermark_pages = max(0, admission_watermark_pages)
        self._active: dict[int, Request] = {}       # decoding
        self._prefilling: deque[Request] = deque()  # admitted, chunks pending
        # Prefilled requests awaiting their (batched) first-token sample:
        # a burst of arrivals costs ONE sampling sync, not one each.
        self._pending_first: list[tuple[Request, Any]] = []
        self._waiting: deque[Request] = deque()
        self._lock = threading.Lock()
        self._counter = itertools.count()
        self._handle_counter = itertools.count(1)
        # Host-side mirrors of decode-step inputs. Block tables default to
        # the slot's trash page so inactive slots never corrupt live pages.
        self._tokens = np.zeros(max_slots, np.int32)
        self._pos = np.zeros(max_slots, np.int32)
        self._block_tables = np.tile(
            np.arange(max_slots, dtype=np.int32)[:, None], (1, self.max_pages_per_seq)
        )
        # Copy-on-write prefix sharing (partial tail blocks) needs the
        # executor's page-copy op + row-granular prefill writes; full
        # page-aligned block sharing works everywhere.
        self._cow_enabled = (enable_prefix_cache and
                             getattr(executor, "supports_prefix_cow", False))
        # Tiered KV (host-RAM spill tier under the device page pool):
        # refcount-0 trie pages about to be evicted export to a bounded
        # host cache keyed by chain hash, and a future match_prefix miss
        # restores them into fresh pages instead of recomputing. 0
        # disables the tier (evicted pages just die, as before).
        self.host_kv_cache_pages = max(0, host_kv_cache_pages)
        self._host_kv: "OrderedDict[bytes, dict]" = OrderedDict()
        if self.host_kv_cache_pages and enable_prefix_cache and \
                getattr(executor, "supports_kv_migration", False):
            self.allocator.on_evict = self._spill_page_to_host
        self.metrics = {"prefix_hit_pages": 0, "prefix_lookup_pages": 0,
                        # True-reuse accounting: prompt tokens served from
                        # shared pages (full blocks + partial tails) vs
                        # prompt tokens admitted, and COW fork count.
                        "prefix_cached_tokens": 0, "prompt_tokens": 0,
                        "cow_forks": 0,
                        "prefill_chunks": 0,
                        "decode_steps": 0, "decode_dispatches": 0,
                        # Per-step schedule mix: how many engine steps ran
                        # fused prefill+decode vs either alone (plus
                        # first-token flush-only steps).
                        "engine_step_mix": {"mixed": 0, "prefill": 0,
                                            "decode": 0, "flush": 0},
                        # Steps where live decode streams waited behind a
                        # prefill-only dispatch (0 under mixed dispatch —
                        # the number the token budget exists to kill).
                        "decode_stall_steps": 0,
                        # Engine operations streamed through a persistent
                        # compiled loop (dag/loop.py) instead of per-tick
                        # actor RPC — nonzero exactly when the executor
                        # drives a loop (sharded pp path).
                        "dag_loop_ticks": 0,
                        # KV-page migration (disaggregated serving / spill
                        # migration): pages shipped out of / into this
                        # engine's pool, migration round counts, import
                        # bytes, and reservation failures that fell back
                        # to a cold prefill.
                        "kv_pages_exported": 0, "kv_pages_imported": 0,
                        "kv_migrations_out": 0, "kv_migrations_in": 0,
                        "kv_import_failures": 0, "kv_import_bytes": 0,
                        # Tiered KV: evicted trie pages spilled to host
                        # RAM and pages restored from it on a later hit.
                        "host_kv_spilled_pages": 0,
                        "host_kv_restored_pages": 0,
                        # Overload protection: deadline expiries by where
                        # the request was (queued = never touched the
                        # engine; running = aborted mid-prefill/decode,
                        # pages freed the same tick), bounded-queue sheds,
                        # and admission-watermark refusals (the request
                        # stays queued, never bounces to the client).
                        "deadline_expired_queued": 0,
                        "deadline_expired_running": 0,
                        "queue_rejects": 0,
                        "admission_rejects": 0,
                        # Tenancy: admissions deferred because every
                        # HBM-resident adapter was pinned by an in-flight
                        # request (the request waits, it is not failed).
                        "adapter_defers": 0,
                        # Speculative decoding: drafted tokens sent to
                        # verification, drafts the target accepted,
                        # tokens emitted by verify dispatches, verify
                        # dispatch count, and slot-rounds that discarded
                        # at least one drafted token (the rollback — its
                        # staged K/V committed to the trash page, never
                        # a pool page).
                        "spec_drafted_tokens": 0,
                        "spec_accepted_tokens": 0,
                        "spec_emitted_tokens": 0,
                        "spec_dispatches": 0,
                        # (dispatch, active slot) pairs — the
                        # denominator of spec_tokens_per_dispatch, so
                        # the ratio is per-sequence per-forward (1.0 =
                        # plain decode), independent of batch size.
                        "spec_slot_rounds": 0,
                        "spec_rollbacks": 0,
                        # Weight residency (always-warm fleet): demotions
                        # of the weight pytree to host RAM, promotions
                        # back to device (device_put, not a reload), and
                        # the last promotion's wall time.
                        "weights_demoted": 0, "weights_promoted": 0,
                        "weight_promote_ms": 0.0,
                        # Flight recorder: request timelines dumped as
                        # llm.request_timeline spans on SLO breach
                        # (deadline expiry, shed, TTFT-SLO breach) —
                        # at most one dump per request.
                        "timeline_dumps": 0}
        # Last few breach dumps, for serve.status() "last-breach" rows
        # (the full event payload lives in the span store).
        self._breach_samples: deque[dict] = deque(maxlen=8)
        # Weight residency (always-warm fleet): the host-RAM copy of the
        # weight pytree while demoted. The lock serializes demote /
        # promote against each other and against admission's lazy
        # re-promotion.
        self._host_params = None
        self._residency_lock = threading.Lock()

    @staticmethod
    def total_pages(max_slots: int, max_len: int, page_size: int,
                    num_pages: int | None = None) -> int:
        """Pool size: per-slot trash pages + usable pages (default: enough
        for every slot to hold a full-length sequence). Exposed so a
        remote executor (multi-host shards) can be pre-built with the same
        geometry the engine will assume."""
        usable = num_pages if num_pages is not None else max_slots * (max_len // page_size)
        return max_slots + usable

    # ------------------------------------------------------------- admission
    def add_request(self, request: Request) -> None:
        if len(request.prompt) >= self.max_len:
            raise ValueError(
                f"prompt of {len(request.prompt)} tokens >= max_len {self.max_len}"
            )
        if not request.prompt:
            raise ValueError("empty prompt")
        if request.trace is None:
            from ..observability import tracing

            request.trace = tracing.current_wire()
        # Scale-to-zero wake: the first request onto a demoted engine
        # promotes the host-resident weights before it queues, so its
        # TTFT carries the device_put, not a crash or a cold load.
        self._ensure_weights_resident()
        with self._lock:
            if self.max_queued_requests and \
                    len(self._waiting) >= self.max_queued_requests:
                self.metrics["queue_rejects"] += 1
                err = QueueFullError(
                    f"engine admission queue is full "
                    f"({len(self._waiting)} waiting, bound "
                    f"{self.max_queued_requests})",
                    retry_after=self._queue_retry_after_locked())
                request.timeline.add(loop_recorder.EV_SHED, 0)
                self.dump_timeline(request, "shed_queue_full")
                raise err
            request.timeline.add(loop_recorder.EV_ADMIT, len(request.prompt),
                                 now=request.arrived_wall)
            self._waiting.append(request)

    def _queue_retry_after_locked(self) -> int:
        """Retry-After for a replica-queue shed: the waiting backlog over
        the concurrency the engine actually serves (its slots)."""
        backlog = len(self._waiting) + len(self._prefilling) + \
            len(self._active) + 1
        return max(1, min(60, -(-backlog // max(1, self.max_slots))))

    def cancel(self, request_id: str) -> None:
        with self._lock:
            keep: deque[Request] = deque()
            for r in self._waiting:
                if r.request_id == request_id:
                    r.done, r.finish_reason = True, "cancelled"
                else:
                    keep.append(r)
            self._waiting = keep
            keep = deque()
            for r in self._prefilling:
                if r.request_id == request_id:
                    r.done, r.finish_reason = True, "cancelled"
                    self._retire_locked(r)
                else:
                    keep.append(r)
            self._prefilling = keep
            for slot, r in list(self._active.items()):
                if r.request_id == request_id:
                    r.done, r.finish_reason = True, "cancelled"
                    self._retire_locked(r)
            for r, _h in self._pending_first:
                if r.request_id == request_id and not r.done:
                    r.done, r.finish_reason = True, "cancelled"
                    self._retire_locked(r)  # flush skips done entries

    @property
    def has_work(self) -> bool:
        with self._lock:
            return bool(self._waiting or self._prefilling or self._active
                        or self._pending_first)

    def _retire_locked(self, r: Request) -> None:
        """Free the request's slot and pages (idempotent). Full PROMPT
        pages enter the prefix cache instead of the free list."""
        if r.slot != -1 or r.block_table:
            # First retire only (the guard is the idempotence condition
            # below): close the flight-recorder timeline.
            r.timeline.add(loop_recorder.EV_RETIRE, len(r.generated))
        if r.slot >= 0 and r.slot in self._active:
            self._active.pop(r.slot, None)
            self._free_slots.append(r.slot)
            self._block_tables[r.slot, :] = r.slot  # back to trash page
            self._lora_idx[r.slot] = 0
            # Reset the host pos mirror too: the executor's live_pages
            # bucket is max over ALL slots, and a stale 8k pos from a
            # retired request would inflate every later batch's
            # attention width for the engine's lifetime.
            self._pos[r.slot] = 0
        elif r.slot >= 0 and r.slot in self._free_slots:
            pass  # already retired
        elif r.slot >= 0:
            self._free_slots.append(r.slot)
            self._block_tables[r.slot, :] = r.slot
            self._pos[r.slot] = 0
        if r.lora_slot and self.lora_manager is not None:
            self.lora_manager.release(r.lora_slot)
            r.lora_slot = 0
        if r.block_table:
            if r.pin_for_export and not r.export_pinned:
                # Migration source: keep one ref per page past retire so
                # the exporter can finish streaming them; released by
                # release_export_pins once the transfer ends.
                for pid in r.block_table:
                    self.allocator.share(pid)
                r.export_pinned = list(r.block_table)
            if self.enable_prefix_cache and r.finish_reason != "admission_failed":
                # Register only pages whose K/V was actually COMPUTED: a
                # cancel mid-prefill leaves later prompt pages holding
                # garbage — caching them would poison future prefix hits.
                # The chain now covers the FULL sequence (prompt +
                # generated tokens — their K/V is a pure function of the
                # token ids, which the chain hash captures), so multi-turn
                # follow-ups whose prompt embeds the previous answer hit
                # too. The last generated token's K/V is never written
                # (it was emitted, not fed back), hence the -1.
                ps = self.page_size
                seq = list(r.prompt) + list(r.generated)
                if r.prefill_pos < len(r.prompt):
                    valid = r.prefill_pos  # cancelled mid-prefill
                else:
                    valid = len(r.prompt) + max(0, len(r.generated) - 1)
                valid = min(valid, len(r.block_table) * ps)
                full_pages = valid // ps
                h = hashlib.sha1()
                # Adapter-specific K/V must never be shared across models
                h.update((r.model or "").encode())
                parent = h.digest()
                for i in range(full_pages):
                    h.update(bytes(np.asarray(
                        seq[i * ps:(i + 1) * ps], np.int32).tobytes()))
                    self.allocator.register_prefix(
                        r.block_table[i], h.digest(), parent)
                    parent = h.digest()
                if self._cow_enabled and full_pages < len(r.block_table):
                    # Partial tail block: cache the raw token run so a
                    # follow-up can map the page read-only and COW-fork
                    # it at its first mid-page write.
                    tail = tuple(int(t) for t in seq[full_pages * ps:valid])
                    if tail:
                        self.allocator.register_partial(
                            parent, tail, r.block_table[full_pages])
            for pid in r.block_table:
                self.allocator.release(pid)
            r.block_table = []
        if r.cow_page is not None:
            # Reserved fork page never used (cancel before the first
            # suffix write): back to the pool.
            self.allocator.release(r.cow_page)
            r.cow_page = None
        r.slot = -1

    # ------------------------------------------------------------------ step
    @property
    def mixed_dispatch_enabled(self) -> bool:
        """True when steps fuse prefill chunks into the decode dispatch
        (token budget > 0 and the executor has the fused entry point).
        A LoRA stack no longer disables this: the fused dispatch's
        decode half carries the per-slot ``lora_idx``, so slots holding
        DIFFERENT adapters decode together in one dispatch — only
        adapter-bound prefills stay legacy (plan selection skips them,
        and an all-adapter prefill queue falls back per step)."""
        return (self.prefill_token_budget > 0
                and getattr(self.executor, "supports_mixed_dispatch", False))

    @property
    def prefix_cache_hit_rate(self) -> float:
        """Fraction of cacheable prompt pages served from the prefix
        cache (hit pages / looked-up pages since engine start). A TRUE
        reuse rate: every hit page is mapped into the slot's table and
        its tokens are skipped by the suffix prefill."""
        lookups = self.metrics.get("prefix_lookup_pages", 0)
        return self.metrics["prefix_hit_pages"] / lookups if lookups else 0.0

    @property
    def prefill_suffix_frac(self) -> float:
        """Fraction of admitted prompt tokens actually prefilled (the
        cold suffix); 1.0 = no prefix reuse. TTFT scales with this."""
        total = self.metrics.get("prompt_tokens", 0)
        if not total:
            return 1.0
        return 1.0 - self.metrics["prefix_cached_tokens"] / total

    @property
    def speculation_enabled(self) -> bool:
        """True when decode ticks run draft + verify: a speculation
        config is set AND the executor has the verify entry point (off
        pp / LoRA — those paths decode plain, exactly as before)."""
        return (self.speculation is not None
                and self._drafter is not None
                and self.lora_manager is None
                and getattr(self.executor, "supports_speculation", False))

    @property
    def spec_accept_rate(self) -> float:
        """Fraction of drafted tokens the target model accepted (0-1
        since engine start). The n-gram drafter's number is traffic-
        dependent: repetitive/multi-turn prompts accept high."""
        drafted = self.metrics.get("spec_drafted_tokens", 0)
        return (self.metrics["spec_accepted_tokens"] / drafted
                if drafted else 0.0)

    @property
    def spec_tokens_per_dispatch(self) -> float:
        """Tokens emitted per slot per verify dispatch — 1.0 is exactly
        what one plain decode forward yields per sequence, so > 1.0
        means speculation is amortizing target-model forwards. The
        accept-0 floor guarantees it never drops below 1.0."""
        n = self.metrics.get("spec_slot_rounds", 0)
        return self.metrics["spec_emitted_tokens"] / n if n else 0.0

    def step(self) -> list[dict]:
        """Advance the engine one tick: admit waiting requests while slots
        and pages allow, then dispatch.

        With mixed dispatch enabled (the default off the pp/LoRA paths),
        a step with both live decoders and pending prefill issues ONE
        fused dispatch: the full ``[max_slots]`` decode burst plus up to
        ``prefill_token_budget`` prompt tokens — prefill rides along with
        decode instead of preempting it, and just-finished prompts flush
        their batched first-token samples the same step.

        The legacy schedule (budget 0, pp meshes, LoRA stacks) runs ONE
        prefill chunk per step strictly ahead of decode, with the
        starvation guard forcing a decode burst after
        ``decode_starvation_limit`` consecutive stalled steps.

        Returns emission events ``{"request_id", "token", "done",
        "finish_reason"}``."""
        if self.has_work:
            # Belt-and-braces for a demote racing admission: no dispatch
            # ever runs against executor.params=None.
            self._ensure_weights_resident()
        expired = self._expire_deadlines()
        if expired:
            return expired + self._step_scheduled()
        return self._step_scheduled()

    def _expire_deadlines(self) -> list[dict]:
        """Overload protection: sweep expired request deadlines at the
        tick boundary. A request that expires while still WAITING never
        touches the engine (no slot, no pages, no prefill) — counter
        ``deadline_expired_queued``; one that expires mid-prefill /
        mid-decode / awaiting its first sample is aborted and retired
        THIS tick, returning its slot, pages, and trie pins to the pool
        — counter ``deadline_expired_running``. Emits a terminal event
        per expiry so streams end promptly with finish_reason
        "deadline"."""
        events: list[dict] = []
        breached: list[Request] = []
        now = time.time()
        with self._lock:
            if self._waiting and any(
                    r.deadline is not None and now >= r.deadline
                    for r in self._waiting):
                keep: deque[Request] = deque()
                for r in self._waiting:
                    if r.deadline is not None and now >= r.deadline:
                        r.done, r.finish_reason = True, "deadline"
                        self.metrics["deadline_expired_queued"] += 1
                        r.timeline.add(loop_recorder.EV_DEADLINE, 0, now=now)
                        breached.append(r)
                        events.append({"request_id": r.request_id,
                                       "token": -1, "done": True,
                                       "finish_reason": "deadline"})
                    else:
                        keep.append(r)
                self._waiting = keep
            expired: list[Request] = []
            if any(r.deadline is not None and now >= r.deadline
                   for r in self._prefilling):
                keep = deque()
                for r in self._prefilling:
                    if r.deadline is not None and now >= r.deadline \
                            and not r.done:
                        expired.append(r)
                    else:
                        keep.append(r)
                self._prefilling = keep
            for r in list(self._active.values()):
                if r.deadline is not None and now >= r.deadline \
                        and not r.done:
                    expired.append(r)
            for r, _h in self._pending_first:
                if r.deadline is not None and now >= r.deadline \
                        and not r.done:
                    expired.append(r)  # the flush drops its handle
            for r in expired:
                r.done, r.finish_reason = True, "deadline"
                r.timeline.add(loop_recorder.EV_DEADLINE, 0, now=now)
                self._retire_locked(r)
                self.metrics["deadline_expired_running"] += 1
                breached.append(r)
                events.append({"request_id": r.request_id, "token": -1,
                               "done": True, "finish_reason": "deadline"})
        for r in expired:
            self._record_decode_span(r)
        for r in breached:
            self.dump_timeline(r, "deadline")
        return events

    def _step_scheduled(self) -> list[dict]:
        self._admit()
        mix = self.metrics["engine_step_mix"]
        with self._lock:
            r = self._prefilling[0] if self._prefilling else None
            has_active = bool(self._active)
        if r is not None and has_active and self.mixed_dispatch_enabled:
            events = self._mixed_step()
            if events is not None:
                mix["mixed"] += 1
                self._starved_steps = 0
                if self._pending_first:
                    events = events + self._flush_first_samples()
                return events
            # no fusable prefill candidate (e.g. all adapter-bound):
            # fall through to the legacy schedule for this step
        if r is not None:
            if (has_active and self.decode_starvation_limit
                    and self._starved_steps >= self.decode_starvation_limit):
                self._starved_steps = 0
                mix["decode"] += 1
                return self._decode_all()
            if has_active:
                self._starved_steps += 1
                self.metrics["decode_stall_steps"] += 1
            events = self._prefill_chunk_one(r)
            mix["prefill"] += 1
            with self._lock:
                drained = not self._prefilling
            if drained and self._pending_first:
                events = events + self._flush_first_samples()
            return events
        self._starved_steps = 0
        if self._pending_first:
            mix["flush"] += 1
            return self._flush_first_samples()
        if self._active:
            mix["decode"] += 1
            return self._decode_all()
        return []

    def _admit(self) -> None:
        admitted: list[Request] = []
        with self._lock:
            while self._waiting and self._free_slots:
                r = self._waiting[0]
                # Worst-case pages so a running request can never OOM the
                # pool mid-decode (admission control replaces page faults).
                total_tokens = len(r.prompt) + r.max_new_tokens
                n_pages = min(
                    (total_tokens + self.page_size - 1) // self.page_size,
                    self.max_pages_per_seq,
                )
                hits: list[int] = []
                partial: tuple[int, int] | None = None
                if self.enable_prefix_cache:
                    # Hit pages (and the partial) arrive PINNED: refcounts
                    # are bumped at match time, before any alloc can run —
                    # alloc's LRU eviction only skips refcount>0 pages, so
                    # an unpinned hit page could be evicted and handed
                    # back as "fresh" (the same physical page at two
                    # block-table positions: silent KV corruption), and
                    # the host-tier restore path allocs mid-match.
                    hits, partial = self._prefix_hits(r)
                # A partial hit does not shrink the reservation: the
                # fresh allocation keeps one spare page as the reserved
                # COW fork target, so the write-triggered fork can never
                # fail under pressure mid-stream.
                #
                # Admission watermark: the worst-case reservation taken
                # HERE is what guarantees a running slot never hits a
                # mid-decode allocation failure because of a newly
                # admitted one — refusing (and counting) below the
                # free-page watermark keeps the request IN the queue
                # (head-of-line wait), never bouncing it to the client.
                if self.allocator.available() < \
                        n_pages - len(hits) + self.admission_watermark_pages:
                    self._unpin_hits_locked(hits, partial)
                    self.metrics["admission_rejects"] += 1
                    break  # head-of-line: wait for pages to free
                self._waiting.popleft()
                fresh = self.allocator.alloc(n_pages - len(hits))
                if fresh is None:  # race-free under lock, but be safe
                    self._unpin_hits_locked(hits, partial)
                    r.done, r.finish_reason = True, "admission_failed"
                    continue
                if partial is not None:
                    # Shared partial tail block maps read-only at the
                    # suffix position; fresh[0] is the reserved fork.
                    r.cow_page = fresh[0]
                    r.partial_len = partial[1]
                    r.block_table = hits + [partial[0]] + fresh[1:]
                else:
                    r.block_table = hits + fresh
                r.shared_pages = len(hits) + (1 if partial is not None else 0)
                r.prefill_pos = len(hits) * self.page_size + (
                    partial[1] if partial is not None else 0)
                r.cached_prefix_tokens = r.prefill_pos
                self.metrics["prefix_hit_pages"] += len(hits)
                self.metrics["prefix_cached_tokens"] += r.prefill_pos
                self.metrics["prompt_tokens"] += len(r.prompt)
                if r.model and self.lora_manager is not None:
                    try:
                        # May read the adapter from storage + write the
                        # device stack; engine-loop blocking is the
                        # admission cost of a cold adapter (LRU-cached
                        # after).
                        r.lora_slot = self.lora_manager.acquire(r.model)
                    except Exception as e:
                        from .tenancy import AdapterCapacityError

                        self._release_admission_locked(r)
                        if isinstance(e, AdapterCapacityError):
                            # Every resident adapter is pinned by an
                            # in-flight request: a QUEUEING condition,
                            # not a client error — the request stays at
                            # the head of the queue until a finishing
                            # request unpins a slot. Back out the reuse
                            # accounting taken above so the retry does
                            # not double-count.
                            self.metrics["prefix_hit_pages"] -= len(hits)
                            self.metrics["prefix_cached_tokens"] -= \
                                r.cached_prefix_tokens
                            self.metrics["prompt_tokens"] -= len(r.prompt)
                            self._waiting.appendleft(r)
                            self.metrics["adapter_defers"] += 1
                            break
                        r.done, r.finish_reason = True, "admission_failed"
                        logger.warning("adapter %r load failed: %s", r.model, e)
                        continue
                elif r.model and self.lora_manager is None:
                    self._release_admission_locked(r)
                    r.done, r.finish_reason = True, "admission_failed"
                    continue
                r.slot = self._free_slots.pop()
                self._lora_idx[r.slot] = r.lora_slot
                self._block_tables[r.slot, :len(r.block_table)] = r.block_table
                self._prefilling.append(r)
                admitted.append(r)
        for r in admitted:
            if r.cached_prefix_tokens:
                r.timeline.add(loop_recorder.EV_PREFIX_HIT,
                               r.cached_prefix_tokens)
            self._record_prefix_match_span(r)

    def _release_admission_locked(self, r: Request) -> None:
        """Undo a half-admitted request's page state (shared refs, fresh
        pages, the reserved COW fork)."""
        for pid in r.block_table:
            self.allocator.release(pid)
        r.block_table = []
        if r.cow_page is not None:
            self.allocator.release(r.cow_page)
            r.cow_page = None
        r.shared_pages = 0
        r.partial_len = 0
        r.prefill_pos = 0

    def _record_prefix_match_span(self, r: Request) -> None:
        """One span per admission: how much of the prompt the prefix
        trie served (full-block hits + partial tail rows) — the
        per-request view behind ``prefix_cache_hit_rate``."""
        if not r.trace:
            return
        from ..observability import tracing

        now = time.time()
        tracing.record_span(tracing.make_span(
            "llm.prefix_match", "llm", r.arrived_wall, now,
            r.trace.get("trace_id", ""), r.trace.get("span_id", ""),
            attrs={"request_id": r.request_id,
                   "prompt_tokens": len(r.prompt),
                   "cached_tokens": r.cached_prefix_tokens,
                   "hit_pages": r.shared_pages,
                   "partial_tokens": r.partial_len}))

    def _prefix_hits(self, r: Request) -> tuple[list[int],
                                                tuple[int, int] | None]:
        """Longest cached chain covering the prompt: full token-block
        pages from the trie, plus (with COW support) the best partial
        tail-block match at the boundary — capped so at least one prompt
        token is always computed (its hidden state seeds sampling — the
        reference caps identically). Returns ``(full_hit_pages,
        (partial_page, matched_rows) | None)``."""
        ps = self.page_size
        max_hit_pages = (len(r.prompt) - 1) // ps
        self.metrics["prefix_lookup_pages"] += max_hit_pages
        root, chain = self._chain_hashes(r.prompt, r.model)
        hashes = chain[:max_hit_pages]
        hits = self.allocator.match_prefix(hashes)
        for pid in hits:
            self.allocator.share(pid)  # pin before anything can alloc
        if self._host_kv and len(hits) < len(hashes):
            hits = self._restore_host_hits(root, hashes, hits)
        partial = None
        if self._cow_enabled:
            parent = hashes[len(hits) - 1] if hits else root
            remainder = r.prompt[len(hits) * ps:]
            # ≥1 computed token AND the matched rows must stay a strict
            # sub-page (a full page would be a full-block hit).
            cap = min(len(remainder) - 1, ps - 1)
            if cap > 0:
                partial = self.allocator.match_partial(
                    parent, tuple(int(t) for t in remainder), cap)
                if partial is not None:
                    self.allocator.share(partial[0])
        return hits, partial

    def _unpin_hits_locked(self, hits: list[int],
                           partial: tuple[int, int] | None) -> None:
        """Drop the pins ``_prefix_hits`` took when admission cannot use
        them (head-of-line wait, reservation failure) — the pages stay
        cached for the retry."""
        for pid in hits:
            self.allocator.release(pid)
        if partial is not None:
            self.allocator.release(partial[0])

    def _chain_hashes(self, tokens, model: str | None = None
                      ) -> tuple[bytes, list[bytes]]:
        """Adapter-scoped root hash plus the chain hash of every FULL
        token block of ``tokens`` — the trie's path identities. Shared by
        admission matching, page export, and import re-registration, so
        a page migrated between engines lands under byte-identical
        hashes on both sides."""
        ps = self.page_size
        h = hashlib.sha1()
        h.update((model or "").encode())  # adapter-scoped prefix space
        root = h.digest()
        hashes: list[bytes] = []
        for i in range(len(tokens) // ps):
            h.update(bytes(np.asarray(
                tokens[i * ps:(i + 1) * ps], np.int32).tobytes()))
            hashes.append(h.digest())
        return root, hashes

    def _spill_page_to_host(self, page_id: int, chain_hash: bytes) -> None:
        """Tiered-KV eviction hook (runs under the engine lock, inside
        ``PageAllocator._evict_one``): pull the doomed page's K/V to host
        RAM keyed by its chain hash, bounded LRU."""
        try:
            data = self.executor.export_pages([page_id])
        except Exception:
            return  # spill is best-effort; eviction proceeds regardless
        self._host_kv[chain_hash] = data
        self._host_kv.move_to_end(chain_hash)
        while len(self._host_kv) > self.host_kv_cache_pages:
            self._host_kv.popitem(last=False)
        self.metrics["host_kv_spilled_pages"] += 1

    def _restore_host_hits(self, root: bytes, hashes: list[bytes],
                           hits: list[int]) -> list[int]:
        """Extend a trie match with pages restored from the host-RAM
        spill tier: each restored page is scattered back into a fresh
        pool page and re-registered under its chain hash, so the suffix
        prefill skips it exactly like a device-resident hit. The caller
        pinned every prior hit, and each restored page keeps its alloc
        ref (= the pin), so the LRU eviction a restore's alloc may
        trigger can never recycle any page of this match."""
        while len(hits) < len(hashes):
            h = hashes[len(hits)]
            data = self._host_kv.get(h)
            if data is None:
                break
            got = self.allocator.alloc(1)
            if got is None:
                break
            (pid,) = got
            del self._host_kv[h]  # single copy: it lives on-device again
            try:
                self.executor.import_pages([pid], data)
            except Exception:
                self.allocator.release(pid)
                break
            parent = hashes[len(hits) - 1] if hits else root
            self.allocator.register_prefix(pid, h, parent)
            hits.append(pid)  # alloc ref doubles as the hit pin
            self.metrics["host_kv_restored_pages"] += 1
        return hits

    def _chunk_bucket(self, n: int) -> int:
        b = self.page_size
        while b < n and b < self.prefill_chunk_size:
            b *= 2
        return min(b, self.prefill_chunk_size)

    def _maybe_cow(self, r: Request) -> None:
        """Write-triggered copy-on-write: the next suffix chunk starts at
        ``prefill_pos``; when that position's page is still a SHARED
        partial tail block, fork it now — device-copy the one page into
        the fork reserved at admission, swap the slot's table entry, and
        drop our ref on the shared original (which stays immutable for
        its other readers). Never copies the pool, only the page."""
        if r.cow_page is None:
            return
        with self._lock:
            if r.done or not r.block_table:
                return
            idx = r.prefill_pos // self.page_size
            if idx >= r.shared_pages:
                # Pure full-block sharing after all (defensive): the
                # reserve is never written — return it to the pool.
                self.allocator.release(r.cow_page)
                r.cow_page = None
                return
            old, new = r.block_table[idx], r.cow_page
            # Copy before the swap is visible anywhere: the executor op
            # rides the ordered dispatch stream, so every shard forks the
            # rows before the chunk that writes past them.
            self.executor.copy_pages([old], [new])
            r.block_table[idx] = new
            self._block_tables[r.slot, idx] = new
            self.allocator.release(old)
            r.shared_pages = idx
            r.cow_page = None
            self.metrics["cow_forks"] += 1
            r.timeline.add(loop_recorder.EV_COW_FORK, new)

    def _prefill_chunk_one(self, r: Request) -> list[dict]:
        self._maybe_cow(r)
        remaining = len(r.prompt) - r.prefill_pos
        bt = np.full(self.max_pages_per_seq, r.slot, np.int32)  # trash-pad
        bt[:len(r.block_table)] = r.block_table
        # Chunk-pipelined prefill: an executor that can pipeline (pp
        # stages) takes up to `depth` consecutive FULL-size chunks of
        # this prompt in ONE dispatch — the single-chunk schedule leaves
        # (pp-1)/pp of prefill compute idle.
        depth = getattr(self.executor, "pipelined_prefill_depth", 1)
        full = self.prefill_chunk_size
        m = min(depth, remaining // full,
                (self.max_len - r.prefill_pos) // full)
        # power-of-two wavefront lengths: O(log depth) compiled variants
        while m & (m - 1):
            m &= m - 1
        if m >= 2 and not r.lora_slot:
            take = m * full
            tokens_m = np.asarray(
                r.prompt[r.prefill_pos:r.prefill_pos + take],
                np.int32).reshape(m, full)
            final = r.prefill_pos + take >= len(r.prompt)
            handle = (next(self._handle_counter)
                      if final and not r.prefill_only else None)
            self.executor.prefill_many(bt, tokens_m, r.prefill_pos, handle, full)
            self.metrics["prefill_chunks"] += m
            r.prefill_pos += take
            r.timeline.add(loop_recorder.EV_PREFILL_CHUNK, take)
        else:
            # Bucket, clamped so the chunk's pages never run past the
            # table (both operands are page-aligned).
            chunk = min(self._chunk_bucket(remaining),
                        self.max_len - r.prefill_pos)
            tokens = np.zeros(chunk, np.int32)
            take = min(remaining, chunk)
            tokens[:take] = r.prompt[r.prefill_pos:r.prefill_pos + take]
            final = r.prefill_pos + take >= len(r.prompt)
            handle = (next(self._handle_counter)
                      if final and not r.prefill_only else None)
            self.executor.prefill(bt, tokens, r.prefill_pos, handle, take,
                                  lora_slot=r.lora_slot)
            self.metrics["prefill_chunks"] += 1
            r.prefill_pos += take
            r.timeline.add(loop_recorder.EV_PREFILL_CHUNK, take)
        if not final:
            return []  # more chunks to go
        # Prompt complete: queue the last real position's hidden state
        # (stashed device-side under `handle`) for BATCHED first-token
        # sampling — a burst of prefills costs one sampling sync total.
        with self._lock:
            if r.done:  # cancelled mid-prefill
                if handle is not None:
                    self.executor.drop_handle(handle)
                if self._prefilling and self._prefilling[0] is r:
                    self._prefilling.popleft()
                return []
            self._prefilling.popleft()
            if r.prefill_only:
                # Disaggregated prefill: the prompt's KV is in the pool
                # and (at retire) the prefix trie — nothing is sampled
                # here; a decode replica imports the pages and samples.
                r.done, r.finish_reason = True, "prefilled"
                self._retire_locked(r)
        if r.prefill_only:
            return [{"request_id": r.request_id, "token": -1, "done": True,
                     "finish_reason": "prefilled"}]
        self._pending_first.append((r, handle))
        return []

    def _flush_first_samples(self) -> list[dict]:
        """One dispatch + one sync samples the first token for every
        pending just-prefilled request."""
        pending, self._pending_first = self._pending_first, []
        live = [(r, h) for r, h in pending if not r.done]
        for r, h in pending:
            if r.done:  # cancelled mid-prefill: free the stashed hidden
                self.executor.drop_handle(h)
        if not live:
            return []
        m = len(live)
        temps = np.asarray([r.temperature for r, _ in live], np.float32)
        tokens = self.executor.sample_first([h for _, h in live], temps)
        events = []
        now = time.monotonic()
        now_wall = time.time()
        for i, (r, _) in enumerate(live):
            with self._lock:
                if r.done:  # cancelled while sampling
                    continue
                self._active[r.slot] = r
            r.pos = len(r.prompt)
            r.first_token_at = now
            r.first_token_wall = now_wall
            r.timeline.add(loop_recorder.EV_FIRST_TOKEN, r.prefill_pos,
                           now=now_wall)
            self._record_prefill_span(r)
            events.append(self._emit(r, int(tokens[i])))
        return events

    def _record_prefill_span(self, r: Request) -> None:
        """Span from request arrival to its first sampled token: the
        engine-side TTFT (queue wait + chunked prefill + first sample)."""
        if not r.trace:
            return
        from ..observability import tracing

        tracing.record_span(tracing.make_span(
            "llm.prefill", "llm", r.arrived_wall, r.first_token_wall or time.time(),
            r.trace.get("trace_id", ""), r.trace.get("span_id", ""),
            attrs={"request_id": r.request_id,
                   "prompt_tokens": len(r.prompt),
                   "cached_prefix_tokens": r.cached_prefix_tokens}))

    # -------------------------------------------------------- flight recorder
    def dump_timeline(self, r: Request, reason: str) -> bool:
        """Dump one request's flight-recorder timeline as a single
        ``llm.request_timeline`` span (attrs carry the full event list:
        admission → prefix hits → prefill chunks → first token →
        per-token deltas → terminal event). Fires AT MOST ONCE per
        request — the first SLO breach (deadline expiry, shed, TTFT-SLO
        breach from the serving layer) wins; later triggers are no-ops.
        Returns True when a dump was recorded."""
        tl = r.timeline
        if tl is None or tl.dumped:
            return False
        tl.dumped = True
        from ..observability import tracing

        payload = tl.to_payload()
        trace = r.trace or {}
        now = time.time()
        tracing.record_span(tracing.make_span(
            "llm.request_timeline", "llm",
            payload["start"] or r.arrived_wall, now,
            trace.get("trace_id") or tracing.new_trace_id(),
            trace.get("span_id", ""),
            attrs={"request_id": r.request_id, "reason": reason,
                   "model": r.model or "", **payload}))
        self.metrics["timeline_dumps"] += 1
        self._breach_samples.append({
            "request_id": r.request_id, "reason": reason, "ts": now,
            "model": r.model or "", "n_events": payload["n_events"],
            "overflowed": payload["overflowed"],
            "events": payload["events"][-16:]})
        return True

    def breach_samples(self) -> list[dict]:
        """Most recent breach dumps (bounded), for serve.status() rows."""
        return list(self._breach_samples)

    def _record_decode_span(self, r: Request) -> None:
        if not r.trace:
            return
        from ..observability import tracing

        now = time.time()
        tracing.record_span(tracing.make_span(
            "llm.decode", "llm", r.first_token_wall or now, now,
            r.trace.get("trace_id", ""), r.trace.get("span_id", ""),
            attrs={"request_id": r.request_id,
                   "generated_tokens": len(r.generated),
                   "finish_reason": r.finish_reason}))
        if r.spec_drafted or r.spec_rollbacks:
            # One llm.speculate span per request that speculation
            # touched: how much the drafter proposed, how much the
            # target accepted, and how many rounds rolled back.
            tracing.record_span(tracing.make_span(
                "llm.speculate", "llm", r.first_token_wall or now, now,
                r.trace.get("trace_id", ""), r.trace.get("span_id", ""),
                attrs={"request_id": r.request_id,
                       "drafted_tokens": r.spec_drafted,
                       "accepted_tokens": r.spec_accepted,
                       "rollbacks": r.spec_rollbacks}))

    def _decode_batch_args(self, active: dict):
        """Fill the host mirrors for one decode burst over ``active`` and
        return the per-slot (temps, eos_ids, remaining) arrays."""
        temps = np.ones(self.max_slots, np.float32)
        eos_ids = np.full(self.max_slots, -1, np.int32)
        remaining = np.zeros(self.max_slots, np.int32)
        for slot, r in active.items():
            self._tokens[slot] = r.generated[-1]
            self._pos[slot] = r.pos
            temps[slot] = r.temperature
            eos_ids[slot] = -1 if r.eos_id is None else r.eos_id
            remaining[slot] = min(
                r.max_new_tokens - len(r.generated),
                len(r.block_table) * self.page_size - r.pos,
            )
        return temps, eos_ids, remaining

    def _emit_decode_events(self, active: dict, tokens, K: int) -> list[dict]:
        events = []
        for k in range(K):
            for slot, r in active.items():
                if r.done:
                    continue
                r.pos += 1
                if r.first_token_at is None:
                    r.first_token_at = time.monotonic()
                    r.first_token_wall = time.time()
                events.append(self._emit(r, int(tokens[k, slot])))
        return events

    def _decode_all(self) -> list[dict]:
        if self.speculation_enabled:
            events = self._speculative_decode()
            if events is not None:
                return events
            # no slot produced a draft this round: the plain fused burst
            # below is strictly better than an all-rejected verify
        with self._lock:
            active = dict(self._active)
        if not active:
            return []
        temps, eos_ids, remaining = self._decode_batch_args(active)
        # K fused decode+sample steps in ONE dispatch, ONE host sync
        # (on-device lax.scan). Finished slots redirect writes to trash;
        # their surplus tokens are discarded below.
        K = self.decode_steps_per_dispatch
        tokens = self.executor.decode(
            self._block_tables, self._tokens, self._pos, temps, eos_ids,
            remaining, K, lora_idx=self._lora_idx,
        )  # [K, slots]
        self.metrics["decode_steps"] += K
        # One dispatch == one staging-buffer commit on the paged path:
        # the pool is written decode_dispatches times, not decode_steps.
        self.metrics["decode_dispatches"] += 1
        self._note_loop_ticks()
        return self._emit_decode_events(active, tokens, K)

    def _speculative_decode(self) -> list[dict] | None:
        """One speculation round: draft K tokens per active slot on the
        host (n-gram lookup over each request's own token history — no
        model cost), then ONE verify dispatch scores all K+1 positions
        per slot and emits the accepted run plus one corrected/bonus
        token. Per-slot accept lengths vary freely inside the batch; a
        slot whose draft is fully rejected still advances one token, so
        a verify never emits less per slot than a single decode step.
        Returns None when no slot drafted anything — the caller falls
        back to the plain fused decode burst for this tick."""
        with self._lock:
            active = dict(self._active)
        if not active:
            return []
        K = self.speculation.num_draft_tokens
        temps, eos_ids, remaining = self._decode_batch_args(active)
        tok_mat = np.full((self.max_slots, K + 1), -1, np.int32)
        tok_mat[:, 0] = self._tokens
        drafted: dict[int, int] = {}
        for slot, r in active.items():
            d = self._drafter.draft(list(r.prompt) + list(r.generated), K)
            if d:
                d = d[:K]
                tok_mat[slot, 1:1 + len(d)] = d
                drafted[slot] = len(d)
        if not drafted:
            return None
        toks, live = self.executor.verify(
            self._block_tables, tok_mat, self._pos, temps, eos_ids,
            remaining)  # [K+1, slots] each
        self.metrics["spec_dispatches"] += 1
        self.metrics["decode_dispatches"] += 1
        self._note_loop_ticks()
        return self._emit_speculative_events(active, toks, live, drafted)

    def _emit_speculative_events(self, active: dict, toks, live,
                                 drafted: dict) -> list[dict]:
        """Emit each slot's verified run in step order (mirrors
        ``_emit_decode_events``): rows stop at the slot's first non-live
        step, and host-side terminators (stop_ids via ``_emit``) discard
        any surplus device rows exactly like the plain decode loop."""
        events: list[dict] = []
        S = toks.shape[0]
        for slot, r in active.items():
            emitted = 0
            for j in range(S):
                if r.done or not live[j, slot]:
                    break
                r.pos += 1
                if r.first_token_at is None:
                    r.first_token_at = time.monotonic()
                    r.first_token_wall = time.time()
                events.append(self._emit(r, int(toks[j, slot])))
                emitted += 1
            dr = drafted.get(slot, 0)
            accepted = min(max(0, emitted - 1), dr)
            if dr:
                r.timeline.add(loop_recorder.EV_SPEC_ROUND, accepted)
            r.spec_drafted += dr
            r.spec_accepted += accepted
            self.metrics["spec_drafted_tokens"] += dr
            self.metrics["spec_accepted_tokens"] += accepted
            self.metrics["spec_emitted_tokens"] += emitted
            self.metrics["spec_slot_rounds"] += 1
            if dr and accepted < dr:
                r.spec_rollbacks += 1
                self.metrics["spec_rollbacks"] += 1
        return events

    def _note_loop_ticks(self) -> None:
        """Mirror the executor's compiled-loop tick count (zero-RPC
        steady-state dispatch, dag/loop.py) into the engine metrics."""
        ticks = getattr(self.executor, "loop_ticks", None)
        if ticks is not None:
            self.metrics["dag_loop_ticks"] = ticks

    def _select_prefill_plans(self) -> list[dict]:
        """Chunks riding the next mixed dispatch: walk the prefill queue
        in admission order, taking one chunk per prompt until the token
        budget or ``max_prefill_seqs_per_step`` is spent. Chunk sizes are
        the SAME buckets as the standalone prefill path (a budget smaller
        than the natural bucket drops to the largest fitting bucket), so
        mixed dispatch adds no new prefill shapes — only combinations."""
        plans: list[dict] = []
        budget = self.prefill_token_budget
        with self._lock:
            queue = [r for r in self._prefilling if not r.done]
        for r in queue:
            if len(plans) >= self.max_prefill_seqs_per_step:
                break
            if budget < self.page_size:
                break
            if r.lora_slot:
                continue  # adapter prefill stays on the legacy path
            self._maybe_cow(r)  # fork a shared tail before writing it
            remaining = len(r.prompt) - r.prefill_pos
            chunk = self._chunk_bucket(remaining)
            if chunk > budget:
                b = self.page_size
                while b * 2 <= budget:
                    b *= 2
                chunk = b
            # Clamp so the chunk's pages never run past the table (same
            # clamp as the standalone path — both operands page-aligned).
            chunk = min(chunk, self.max_len - r.prefill_pos)
            take = min(remaining, chunk)
            if take <= 0:
                continue
            bt = np.full(self.max_pages_per_seq, r.slot, np.int32)
            bt[:len(r.block_table)] = r.block_table
            tokens = np.zeros(chunk, np.int32)
            tokens[:take] = r.prompt[r.prefill_pos:r.prefill_pos + take]
            final = r.prefill_pos + take >= len(r.prompt)
            plans.append({
                "request": r, "block_table": bt, "tokens": tokens,
                "start_pos": r.prefill_pos,
                "handle": (next(self._handle_counter)
                           if final and not r.prefill_only else None),
                "take": take, "final": final,
            })
            budget -= chunk
        return plans

    def _mixed_step(self) -> list[dict] | None:
        """ONE fused dispatch: the full decode burst plus the selected
        prefill chunks. Returns the decode emission events, or None when
        no prefill chunk was fusable (caller falls back to the legacy
        schedule for this step)."""
        plans = self._select_prefill_plans()
        if not plans:
            return None
        with self._lock:
            active = dict(self._active)
        if not active:
            return None  # decoders finished since the caller looked
        temps, eos_ids, remaining = self._decode_batch_args(active)
        K = self.decode_steps_per_dispatch
        wire = [{k: p[k] for k in ("block_table", "tokens", "start_pos",
                                   "handle", "take")} for p in plans]
        tokens = self.executor.mixed(
            wire, self._block_tables, self._tokens, self._pos, temps,
            eos_ids, remaining, K, lora_idx=self._lora_idx,
        )  # [K, slots]
        self.metrics["decode_steps"] += K
        self.metrics["decode_dispatches"] += 1
        # Prefill bookkeeping AFTER the dispatch (mirrors
        # _prefill_chunk_one): advance positions, move finished prompts to
        # the batched first-token queue, drop handles of cancelled ones.
        extra_events: list[dict] = []
        for p in plans:
            r = p["request"]
            self.metrics["prefill_chunks"] += 1
            r.prefill_pos = p["start_pos"] + p["take"]
            r.timeline.add(loop_recorder.EV_PREFILL_CHUNK, p["take"])
            if not p["final"]:
                continue
            with self._lock:
                try:
                    self._prefilling.remove(r)
                except ValueError:
                    pass  # cancel() already rebuilt the queue without it
                if r.done:  # cancelled mid-dispatch
                    if p["handle"] is not None:
                        self.executor.drop_handle(p["handle"])
                    continue
                if r.prefill_only:
                    r.done, r.finish_reason = True, "prefilled"
                    self._retire_locked(r)
                    extra_events.append(
                        {"request_id": r.request_id, "token": -1,
                         "done": True, "finish_reason": "prefilled"})
                    continue
            self._pending_first.append((r, p["handle"]))
        return self._emit_decode_events(active, tokens, K) + extra_events

    def _emit(self, r: Request, token: int) -> dict:
        r.generated.append(token)
        # Per-token ITL record: deltas between consecutive EV_TOKEN
        # timestamps are the inter-token latencies in the dump.
        r.timeline.add(loop_recorder.EV_TOKEN, len(r.generated))
        if (r.eos_id is not None and token == r.eos_id) or token in r.stop_ids:
            r.done, r.finish_reason = True, "stop"
        elif len(r.generated) >= r.max_new_tokens:
            r.done, r.finish_reason = True, "length"
        elif r.pos >= min(self.max_len, len(r.block_table) * self.page_size) - 1:
            r.done, r.finish_reason = True, "max_len"
        if r.done:
            with self._lock:
                self._retire_locked(r)  # idempotent if cancel() beat us
            self._record_decode_span(r)
        return {
            "request_id": r.request_id,
            "token": token,
            "done": r.done,
            "finish_reason": r.finish_reason,
        }

    def pool_stats(self) -> dict:
        """Page-pool accounting snapshot: free pages, cached (trie)
        pages, and pages still PINNED (refcount > 0, i.e. held by a live
        slot, an export pin, or a prefix-hit pin). After every request
        settles — including mid-decode deadline aborts — ``pinned`` must
        return to 0 and ``active_slots`` to 0: the chaos overload plan's
        refcounts-at-baseline invariant."""
        with self._lock:
            cached = len(self.allocator.page_hash) + \
                len(self.allocator._partial_pages)
            pinned = sum(1 for _p, c in self.allocator.refcount.items()
                         if c > 0)
            return {
                "num_pages": self.num_pages,
                "free": len(self.allocator.free),
                "cached": cached,
                "pinned": pinned,
                "active_slots": len(self._active),
                "prefilling": len(self._prefilling),
                "waiting": len(self._waiting),
            }

    # ------------------------------------------------------- weight residency
    @property
    def supports_weight_residency(self) -> bool:
        """Host-tier weight demotion (``llm/weights.py``): the executor
        must own a ``params`` pytree it lets us swap (single-device
        ``LocalEngineExecutor``; sharded/pp executors place their own)."""
        return bool(getattr(self.executor, "supports_weight_residency",
                            False)) and hasattr(self.executor, "params")

    def weights_resident(self) -> bool:
        """True while the weight pytree is on device (normal serving)."""
        return getattr(self.executor, "params", None) is not None

    def demote_weights_to_host(self) -> dict:
        """Standby demotion: copy the weight pytree to host RAM and drop
        the device reference, freeing HBM while the compile cache (and
        the whole engine — pool, trie, adapters) stays warm. Refused
        while any request is in flight — a demote mid-decode would pull
        the weights out from under a dispatch."""
        from . import weights as wlib

        with self._residency_lock:
            if not self.supports_weight_residency:
                return {"ok": False, "reason": "unsupported"}
            if not self.weights_resident():
                return {"ok": True, "already": True, "bytes": 0,
                        "seconds": 0.0}
            if self.has_work:
                return {"ok": False, "reason": "busy"}
            t0 = time.monotonic()
            host = wlib.tree_to_host(self.executor.params)
            self._host_params = host
            self.executor.params = None  # device buffers free on GC
            self.metrics["weights_demoted"] += 1
            # Scale-to-zero reclaims the adapter stack too: no request
            # is in flight, so every resident adapter is unpinned.
            adapters = (self.lora_manager.unload_idle()
                        if self.lora_manager is not None else 0)
            return {"ok": True, "bytes": wlib.tree_bytes(host),
                    "adapters_unloaded": adapters,
                    "seconds": round(time.monotonic() - t0, 6)}

    def promote_weights_from_host(self) -> dict:
        """Standby promotion: ``device_put`` the host copy back. The
        host copy is KEPT (weights are immutable under inference) so the
        next demotion is a pointer drop, not another device pull."""
        from . import weights as wlib

        with self._residency_lock:
            return self._promote_locked(wlib)

    def _promote_locked(self, wlib) -> dict:
        if self.weights_resident():
            return {"ok": True, "already": True, "seconds": 0.0}
        if self._host_params is None:
            return {"ok": False, "reason": "no_host_copy"}
        t0 = time.monotonic()
        params = wlib.host_to_device(self._host_params)
        try:
            import jax

            jax.block_until_ready(params)  # honest promote timing
        except Exception:
            pass
        self.executor.params = params
        dt = time.monotonic() - t0
        self.metrics["weights_promoted"] += 1
        self.metrics["weight_promote_ms"] = round(dt * 1000.0, 3)
        return {"ok": True, "seconds": round(dt, 6)}

    def install_weights(self, host_tree) -> dict:
        """Adopt a weight pytree delivered over the broadcast wire
        (``receive_weight_stream``): it becomes the host copy, then
        promotes if the engine is currently demoted. A resident engine
        only refreshes its host copy — live dispatches keep their
        device tree until the next demote/promote cycle."""
        from . import weights as wlib

        with self._residency_lock:
            if not self.supports_weight_residency:
                return {"ok": False, "reason": "unsupported"}
            self._host_params = wlib.tree_to_host(host_tree)
            if self.weights_resident():
                return {"ok": True, "resident": True, "seconds": 0.0}
            return self._promote_locked(wlib)

    def _ensure_weights_resident(self) -> None:
        """First-request promotion: admission and the step loop call
        this so a request that lands on a demoted (scale-to-zero'd)
        engine pays one device_put, never a crash."""
        if self.weights_resident() or self._host_params is None:
            return
        from . import weights as wlib

        with self._residency_lock:
            self._promote_locked(wlib)

    # ----------------------------------------------------------- KV migration
    @property
    def supports_kv_migration(self) -> bool:
        """Page export/import between engines: needs the prefix trie (the
        registration target) and an executor with the host gather/scatter
        path (off pp; see ``LocalEngineExecutor.supports_kv_migration``)."""
        return bool(self.enable_prefix_cache and
                    getattr(self.executor, "supports_kv_migration", False))

    def pin_prefix_for_export(self, prompt,
                              model: str | None = None) -> dict | None:
        """Match ``prompt``'s longest cached chain — full trie blocks
        plus the best partial tail — and PIN its pages for export: one
        extra refcount per page so pool pressure cannot recycle them
        mid-transfer. Returns the export plan ``{"page_ids", "tokens",
        "full_pages", "partial_len", "model"}`` (release with
        ``release_export_pages``), or None when nothing is cached (or
        migration is unsupported)."""
        if not self.supports_kv_migration or len(prompt) < 2:
            return None
        ps = self.page_size
        with self._lock:
            root, chain = self._chain_hashes(prompt, model)
            hashes = chain[:(len(prompt) - 1) // ps]
            hits = self.allocator.match_prefix(hashes)
            partial = None
            if self._cow_enabled:
                parent = hashes[len(hits) - 1] if hits else root
                remainder = prompt[len(hits) * ps:]
                cap = min(len(remainder) - 1, ps - 1)
                if cap > 0:
                    partial = self.allocator.match_partial(
                        parent, tuple(int(t) for t in remainder), cap)
            if not hits and partial is None:
                return None
            ids = list(hits) + ([partial[0]] if partial is not None else [])
            for pid in ids:
                self.allocator.share(pid)  # pinned until released
        plen = partial[1] if partial is not None else 0
        covered = len(hits) * ps + plen
        return {"page_ids": ids,
                "tokens": [int(t) for t in prompt[:covered]],
                "full_pages": len(hits), "partial_len": plen,
                "model": model or ""}

    def release_export_pages(self, page_ids: list[int]) -> None:
        """Drop the per-page export pins ``pin_prefix_for_export`` took;
        the pages become ordinary evictable cache entries again."""
        with self._lock:
            for pid in page_ids:
                self.allocator.release(pid)

    def export_prefix_kv(self, prompt, model: str | None = None) -> dict | None:
        """Export the cached KV covering ``prompt``'s longest prefix —
        full trie blocks plus the best partial tail — as a host payload
        an ``import_prefix_kv`` on another engine can adopt, in ONE
        blocking pull (the chunked alternative is a
        ``KVMigrationSource.for_cached_prefix`` stream). The pages are
        pinned across the device→host pull so pool pressure cannot
        recycle them mid-export. Returns None when nothing is cached (or
        migration is unsupported)."""
        plan = self.pin_prefix_for_export(prompt, model)
        if plan is None:
            return None
        ids = plan["page_ids"]
        try:
            data = self.executor.export_pages(ids)
        finally:
            self.release_export_pages(ids)
        self.metrics["kv_pages_exported"] += len(ids)
        self.metrics["kv_migrations_out"] += 1
        return {"page_size": self.page_size, "model": plan["model"],
                "tokens": plan["tokens"],
                "full_pages": plan["full_pages"],
                "partial_len": plan["partial_len"],
                "k": data["k"], "v": data["v"]}

    def import_prefix_kv(self, payload: dict | None) -> int:
        """Adopt a migrated KV payload: reserve pages, scatter the data
        in, and register the chain under the same hashes the source used
        — a following ``add_request`` for the same prompt then maps the
        pages as ordinary prefix hits and prefills only the cold suffix.
        Returns the number of prompt tokens now servable from cache; 0
        means clean fallback (pressure, geometry mismatch, unsupported)
        and the caller simply cold-prefills."""
        if not payload or not self.supports_kv_migration \
                or payload.get("page_size") != self.page_size:
            return 0
        full_pages = int(payload.get("full_pages") or 0)
        plen = int(payload.get("partial_len") or 0)
        if not self._cow_enabled:
            plen = 0  # partial tails need row-granular suffix starts
        want = full_pages + (1 if plen else 0)
        if want <= 0:
            return 0
        with self._lock:
            pages = (self.allocator.alloc(want)
                     if self.allocator.available() >= want else None)
        if pages is None:
            # Import under pressure: never evict live sequences' headroom
            # for a cache import — the request cold-prefills instead.
            self.metrics["kv_import_failures"] += 1
            return 0
        k = np.asarray(payload["k"])[:, :want]
        v = np.asarray(payload["v"])[:, :want]
        try:
            self.executor.import_pages(pages, {"k": k, "v": v})
        except Exception:
            with self._lock:
                for pid in pages:
                    self.allocator.release(pid)
            self.metrics["kv_import_failures"] += 1
            return 0
        return self.register_imported_chain(
            pages, payload["tokens"], full_pages, plen,
            model=payload.get("model") or None)

    def register_imported_chain(self, page_ids: list[int], tokens,
                                full_pages: int, partial_len: int,
                                model: str | None = None) -> int:
        """Register freshly imported pages in the prefix trie under the
        chain hashes recomputed from their token ids (self-validating:
        both engines derive identities from the data, not from trust in
        the wire). Callers hold one alloc ref per page; registration
        releases it, leaving the pages cached and immediately matchable.
        A chain link that is ALREADY resident keeps the local page and
        the duplicate import frees straight back to the pool. Returns
        the prompt tokens covered by the (existing + new) chain."""
        ps = self.page_size
        with self._lock:
            root, chain = self._chain_hashes(tokens, model)
            parent = root
            covered = 0
            kept = 0
            for i in range(min(full_pages, len(chain), len(page_ids))):
                h, pid = chain[i], page_ids[i]
                if self.allocator.lookup_prefix(h) is None:
                    self.allocator.register_prefix(pid, h, parent)
                    kept += 1
                self.allocator.release(pid)  # cached if registered, else freed
                parent = h
                covered = (i + 1) * ps
            if partial_len and len(page_ids) > full_pages:
                pid = page_ids[full_pages]
                tail = tuple(int(t) for t in
                             tokens[full_pages * ps:full_pages * ps + partial_len])
                if tail and self._cow_enabled:
                    self.allocator.register_partial(parent, tail, pid)
                self.allocator.release(pid)
                if tail and self.allocator._partials.get(parent, {}) \
                        .get(tail) is not None:
                    # Registered now, or an equivalent entry already
                    # resident — either way those rows are servable.
                    covered += len(tail)
                    if self.allocator._partials[parent][tail] == pid:
                        kept += 1
            self.metrics["kv_pages_imported"] += kept
            if kept or covered:
                self.metrics["kv_migrations_in"] += 1
        return covered

    def release_export_pins(self, r: Request) -> None:
        """Drop the per-page refs ``pin_for_export`` took at retire; the
        pages become ordinary evictable cache entries."""
        with self._lock:
            pins, r.export_pinned = r.export_pinned, []
            for pid in pins:
                self.allocator.release(pid)

    # ------------------------------------------------------------ conveniences
    def generate(self, prompt: list[int], max_new_tokens: int = 32,
                 temperature: float = 0.0, eos_id: int | None = None) -> list[int]:
        """Blocking single-prompt helper (tests / offline use)."""
        rid = f"gen-{next(self._counter)}"
        r = Request(rid, list(prompt), max_new_tokens, temperature, eos_id)
        self.add_request(r)
        while not r.done:
            self.step()
        return r.generated
