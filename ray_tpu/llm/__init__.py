"""TPU-native LLM inference: continuous batching over a paged KV cache.

Equivalent of the reference's ``ray.llm`` serving stack
(``python/ray/llm/_internal/serve/deployments/llm/llm_server.py:415``,
``vllm_engine.py``), which delegates the engine to vLLM. Here the engine is
first-class and TPU-first: the KV cache is a shared page pool indexed by
per-sequence block tables (vLLM's paged attention, recovered with static
shapes: block tables are data, not shapes, so XLA compiles one decode
program and one prefill program per chunk bucket). Chunked prefill bounds
TTFT impact on running streams; hash-matched prompt prefixes reuse pages
without recomputation; token streaming rides the core streaming-generator
protocol through Serve.
"""

from .batch import LLMProcessorConfig, Processor, build_llm_processor
from .engine import InferenceEngine, PageAllocator, Request
from .executor import LocalEngineExecutor
from .lora import LoRAServingConfig, save_adapter
from .migration import KVMigrationSource, receive_kv_stream
from .model import decode_step, init_pages, prefill_chunk
from .multihost import EngineShardWorker, ShardedEngineExecutor, create_sharded_executor
from .serving import LLMDeployment, build_llm_app
from .speculative import Drafter, NgramDrafter, SpeculationConfig
from .tokenizer import ByteTokenizer

__all__ = [
    "KVMigrationSource",
    "receive_kv_stream",
    "InferenceEngine",
    "LocalEngineExecutor",
    "EngineShardWorker",
    "ShardedEngineExecutor",
    "create_sharded_executor",
    "LLMProcessorConfig",
    "Processor",
    "build_llm_processor",
    "PageAllocator",
    "Request",
    "init_pages",
    "LoRAServingConfig",
    "save_adapter",
    "prefill_chunk",
    "decode_step",
    "LLMDeployment",
    "build_llm_app",
    "Drafter",
    "NgramDrafter",
    "SpeculationConfig",
    "ByteTokenizer",
]
