"""TPU-native LLM inference: continuous batching over a slot KV cache.

Equivalent of the reference's ``ray.llm`` serving stack
(``python/ray/llm/_internal/serve/deployments/llm/llm_server.py:415``,
``vllm_engine.py``), which delegates the engine to vLLM. Here the engine is
first-class and TPU-first: instead of vLLM's paged KV with dynamic page
tables (a GPU-pointer-chasing design), the cache is a dense per-slot tensor
— JetStream-style — so every prefill/decode step is a fixed-shape XLA
program that stays on the MXU with zero recompilation at steady state.
"""

from .engine import InferenceEngine, Request
from .model import decode_step, init_cache, prefill
from .serving import LLMDeployment, build_llm_app
from .tokenizer import ByteTokenizer

__all__ = [
    "InferenceEngine",
    "Request",
    "init_cache",
    "prefill",
    "decode_step",
    "LLMDeployment",
    "build_llm_app",
    "ByteTokenizer",
]
