"""Minimal byte-level tokenizer for tests and demos.

The reference gets tokenizers from HuggingFace via vLLM; the engine here
is tokenizer-agnostic (token-id lists in, token-id lists out). This
byte-level fallback keeps the serving path runnable with zero model
assets: ids 0..255 are raw bytes, 256 is BOS, 257 is EOS.
"""

from __future__ import annotations


class ByteTokenizer:
    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [self.bos_id] + ids if bos else ids

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")
