"""Weight residency and delivery: the host-RAM weight tier plus a
chunked weight-broadcast wire.

The fleet subsystem (``serve/fleet.py``) treats replica capacity as a
warm resource, and that only works if the expensive part of a replica —
its weight pytree — can (a) step off the device without being thrown
away and (b) travel to cold replicas without N independent checkpoint
loads. This module provides both halves:

* **Host tier**: :func:`tree_to_host` / :func:`host_to_device` move a
  params pytree between HBM and host RAM, generalizing the PR 11
  host-KV spill tier from pages to weights. A demoted (standby) replica
  keeps its host copy + compile cache; promotion is one ``device_put``
  sweep, not a checkpoint load + compile.

* **Broadcast wire**: :class:`WeightBroadcastSource` streams a params
  pytree over the same credit-bounded ``TcpLoopServer`` the KV
  migration path uses (``llm/migration.py`` — pickled kind-tagged
  chunks, close-after-drain, chaos hook), but with ``n_readers=N`` so N
  cold replicas consume ONE read of the weights. ``_min_acked`` counts
  unconnected readers as cursor 0, so the writer's window throttles to
  the slowest/late-joining reader — true broadcast backpressure.

Wire protocol (pickled dicts, exactly-once, in order):

    {"kind": "meta",  "model", "n_leaves", "total_bytes",
                      "treedef": bytes|None, "fingerprint"}
    {"kind": "chunk", "leaf", "dtype", "shape", "offset", "data"}
    {"kind": "end",   "fingerprint"}                 # complete
    {"kind": "abort"}                                # source failed

Failure is graceful by construction: a receiver that loses the stream
mid-flight (source death, timeout, bad digest) returns ``params=None``
with a status string, and the caller falls back to its host copy or a
direct load — promotion never wedges on a dead broadcaster.
"""

from __future__ import annotations

import hashlib
import pickle
import threading
import time

import numpy as np

from ..dag.channel import ChannelClosed, TcpLoopReader, TcpLoopServer

# One chunk per write keeps the credit window meaningful: 4 MiB chunks x
# 8 slots bounds writer-ahead memory at ~32 MiB regardless of model size.
DEFAULT_CHUNK_BYTES = 4 << 20


def _config():
    from ..core.config import get_config

    return get_config()


def _tree_lib():
    import jax

    return jax.tree_util


# --------------------------------------------------------------- host tier
def tree_to_host(params):
    """Copy every leaf of ``params`` to a host ``np.ndarray`` (the
    standby residency form). Device buffers are NOT freed here — drop
    the device reference after this returns to release HBM."""
    tu = _tree_lib()
    return tu.tree_map(lambda x: np.asarray(x), params)


def host_to_device(host_tree, put=None):
    """Promote a host tree back to device arrays. ``put`` defaults to
    ``jax.device_put`` (replicated single-device form — the executor's
    own ``_put`` handles sharded layouts)."""
    if put is None:
        import jax

        put = jax.device_put
    tu = _tree_lib()
    return tu.tree_map(put, host_tree)


def tree_bytes(params) -> int:
    tu = _tree_lib()
    return sum(int(np.asarray(l).nbytes) for l in tu.tree_leaves(params))


def params_fingerprint(params) -> str:
    """Order-stable content digest of a params pytree: dtype, shape and
    raw bytes of every leaf in flatten order. Byte-parity between a
    broadcast-received tree and a direct load means equal fingerprints."""
    tu = _tree_lib()
    h = hashlib.sha256()
    for leaf in tu.tree_leaves(params):
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


# ---------------------------------------------------------- broadcast wire
class WeightBroadcastSource:
    """Warm-side exporter: snapshots ``params`` to host in the caller's
    thread (so the source stays valid even if the donor replica demotes
    or mutates afterwards), then streams it chunk-by-chunk from a
    background thread to ``n_readers`` consumers.

    Mirrors :class:`~ray_tpu.llm.migration.KVMigrationSource`: same
    channel, same close-after-drain, same ``_die_after_chunks`` chaos
    hook so tests can kill the wire exactly as a dead donor would."""

    def __init__(self, params, model: str = "", n_readers: int = 1,
                 chunk_bytes: int | None = None,
                 advertise: str | None = None,
                 _die_after_chunks: int | None = None):
        tu = _tree_lib()
        leaves, treedef = tu.tree_flatten(params)
        self._leaves = [np.ascontiguousarray(np.asarray(l)) for l in leaves]
        try:
            self._treedef_blob = pickle.dumps(
                treedef, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            # Unpicklable structure: receivers must supply ``like=``.
            self._treedef_blob = None
        self.model = model
        self.fingerprint = params_fingerprint(self._leaves)
        self.chunk_bytes = max(64 << 10, chunk_bytes or DEFAULT_CHUNK_BYTES)
        self._server = TcpLoopServer(n_slots=8, n_readers=max(1, n_readers),
                                     advertise=advertise)
        self._die_after = _die_after_chunks
        self._killed = False
        self.stats = {"leaves": len(self._leaves), "bytes": 0, "chunks": 0,
                      "total_bytes": sum(l.nbytes for l in self._leaves)}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="weight-broadcast-src")
        self._thread.start()

    @property
    def address(self) -> str:
        return self._server.address

    def _send(self, msg: dict) -> None:
        blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        self._server.write(blob, timeout=_config().kv_migration_timeout_s)
        self.stats["bytes"] += len(blob)

    def _run(self) -> None:
        try:
            self._send({"kind": "meta", "model": self.model,
                        "n_leaves": len(self._leaves),
                        "total_bytes": self.stats["total_bytes"],
                        "treedef": self._treedef_blob,
                        "fingerprint": self.fingerprint})
            for i, leaf in enumerate(self._leaves):
                raw = leaf.tobytes()
                off = 0
                # Zero-size leaves still need one chunk so the receiver
                # materializes them.
                while off < len(raw) or off == 0:
                    data = raw[off:off + self.chunk_bytes]
                    self._send({"kind": "chunk", "leaf": i,
                                "dtype": str(leaf.dtype),
                                "shape": tuple(leaf.shape),
                                "offset": off, "data": data})
                    off += max(1, len(data))
                    self.stats["chunks"] += 1
                    if self._die_after is not None \
                            and self.stats["chunks"] >= self._die_after:
                        self._killed = True
                        self._server.close()  # simulated donor death
                        return
                    if off >= len(raw):
                        break
            self._send({"kind": "end", "fingerprint": self.fingerprint})
        except Exception:
            try:
                self._send({"kind": "abort"})
            except Exception:
                pass
        finally:
            try:
                # Close-after-drain: queued chunks (and the end marker)
                # still reach every reader, then they see ChannelClosed.
                self._server.close_writer(timeout=5.0)
            except Exception:
                pass

    def join(self, timeout: float | None = 60.0) -> None:
        self._thread.join(timeout)

    def close(self) -> None:
        self._thread.join(timeout=5.0)
        try:
            self._server.close()
        except Exception:
            pass


def receive_weight_stream(address: str, like=None,
                          timeout_s: float | None = None,
                          connect_timeout: float = 10.0) -> dict:
    """Cold-side importer: pull one weight broadcast into host arrays
    and rebuild the pytree (from the wire's pickled treedef, or from
    ``like``'s structure when the wire carries none).

    Degrades, never fails: any wire error, an incomplete leaf set, or a
    digest mismatch returns ``params=None`` with a ``status`` string so
    the caller falls back to its own load path. Returns
    ``{"params", "bytes", "leaves", "seconds", "complete", "status",
    "fingerprint", "model"}``."""
    t0 = time.monotonic()
    out = {"params": None, "bytes": 0, "leaves": 0, "seconds": 0.0,
           "complete": False, "status": "ok", "fingerprint": "",
           "model": ""}
    if timeout_s is None:
        timeout_s = _config().kv_migration_timeout_s
    n_leaves = 0
    treedef_blob = None
    claimed = ""
    bufs: dict[int, dict] = {}
    reader = None
    try:
        reader = TcpLoopReader(address, connect_timeout=connect_timeout)
        deadline = time.monotonic() + timeout_s
        while True:
            blob = reader.read(timeout=max(0.1, deadline - time.monotonic()))
            out["bytes"] += len(blob)
            msg = pickle.loads(blob)
            kind = msg.get("kind")
            if kind == "meta":
                n_leaves = int(msg.get("n_leaves", 0))
                treedef_blob = msg.get("treedef")
                claimed = msg.get("fingerprint") or ""
                out["model"] = msg.get("model") or ""
            elif kind == "chunk":
                ent = bufs.setdefault(int(msg["leaf"]), {
                    "dtype": msg["dtype"], "shape": msg["shape"],
                    "data": bytearray()})
                # In-order wire: offsets only ever append.
                ent["data"] += msg["data"]
            elif kind == "end":
                out["complete"] = True
                claimed = msg.get("fingerprint") or claimed
                break
            elif kind == "abort":
                out["status"] = "aborted"
                break
    except (ChannelClosed, TimeoutError, ConnectionError, OSError,
            EOFError, pickle.UnpicklingError) as e:
        out["status"] = type(e).__name__
    finally:
        if reader is not None:
            reader.close()
    out["leaves"] = len(bufs)
    if not out["complete"] or len(bufs) != n_leaves or n_leaves == 0:
        if out["status"] == "ok":
            out["status"] = "incomplete"
        out["seconds"] = round(time.monotonic() - t0, 6)
        return out
    leaves = []
    for i in range(n_leaves):
        ent = bufs[i]
        arr = np.frombuffer(bytes(ent["data"]), dtype=np.dtype(ent["dtype"]))
        leaves.append(arr.reshape(ent["shape"]))
    digest = params_fingerprint(leaves)
    out["fingerprint"] = digest
    if claimed and digest != claimed:
        out["status"] = "digest_mismatch"
        out["complete"] = False
        out["seconds"] = round(time.monotonic() - t0, 6)
        return out
    tu = _tree_lib()
    treedef = None
    if treedef_blob:
        try:
            treedef = pickle.loads(treedef_blob)
        except Exception:
            treedef = None
    if treedef is None and like is not None:
        treedef = tu.tree_structure(like)
    if treedef is None:
        out["status"] = "no_structure"
        out["seconds"] = round(time.monotonic() - t0, 6)
        return out
    try:
        out["params"] = tu.tree_unflatten(treedef, leaves)
    except Exception:
        out["status"] = "structure_mismatch"
        out["seconds"] = round(time.monotonic() - t0, 6)
        return out
    out["seconds"] = round(time.monotonic() - t0, 6)
    return out
