"""Engine executors: the device half of the inference engine.

The ``InferenceEngine`` (engine.py) is a host-side scheduler — slots,
pages, prefix cache, admission. Every device interaction goes through an
executor with three operations:

  * ``prefill(block_table, tokens, start_pos, handle, take)`` — run one
    page-aligned prompt chunk; stash the last real position's hidden
    state under ``handle`` (device-resident; no host sync).
  * ``sample_first(handles, temps)`` — batched first-token sampling for
    the stashed hiddens (ONE host sync for a burst of prefills).
  * ``decode(block_tables, tokens, pos, temps, eos_ids, remaining, K)``
    — K fused decode+sample steps, one dispatch, one sync.

``LocalEngineExecutor`` runs on this process's devices (optionally a
mesh: tensor-parallel over local chips, or a global multi-process mesh
after ``jax.distributed.initialize`` — the params/pages are sharded, the
SAME jitted programs run SPMD, XLA inserts the collectives). The
multi-host fan-out lives in ``multihost.py``; the reference gets this
split from vLLM's worker/executor architecture
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py``).
"""

from __future__ import annotations

import functools
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig, PRESETS, init_params
from .model import (copy_pages, decode_loop, init_pages, mixed_dispatch,
                    prefill_chunk, sample_first_batch, verify_block,
                    write_pages)

# Backends with a real Mosaic compiler: the Pallas paged-attention kernel
# runs native. "axon" is the remote-dispatch tunnel to the same chip.
_TPU_BACKENDS = ("tpu", "axon")


def resolve_attention_impl(attention_impl: str = "auto", mesh=None,
                           backend: str | None = None) -> str:
    """Resolve ``attention_impl`` to a concrete decode path.

    ``"auto"`` picks the v2 staging-buffer Pallas kernel (``"paged"``)
    whenever a TPU backend is present — per-slot-proportional HBM traffic
    is the point of the paged design — and falls back to the bucketed
    dense gather (``"dense"``) only when the backend is not a TPU
    (interpret-mode decode is far slower than the dense gather on CPU —
    tests force ``"paged"`` explicitly to exercise the kernel).

    Every mesh shape takes the kernel: tensor-parallel meshes
    shard_map it over the KV-head axis (round 5), pure-pp meshes thread
    the v2 staging carry per stage (round 8,
    ``pp_model.pp_decode_loop``), and composed pp×tp meshes (round 15)
    run the decode loop as ONE flattened manual region over both axes —
    pp manual on layers, tp manual on KV heads — so the kernel runs on
    each shard's local heads and the old "resolves dense on exactly the
    mesh a real v5p slice uses" cliff is gone.
    """
    if attention_impl not in ("auto", "paged", "dense"):
        raise ValueError(f"unknown attention_impl {attention_impl!r}")
    if attention_impl != "auto":
        return attention_impl
    if backend is None:
        backend = jax.default_backend()
    if backend not in _TPU_BACKENDS:
        return "dense"
    return "paged"


class LocalEngineExecutor:
    """Params, page pool, PRNG key and jitted programs on this process's
    devices. With ``mesh``, params/pages shard over it (tp axis) and — for
    a multi-process mesh — sampled-token outputs are pinned to a
    replicated sharding so every process can read them without a gather."""

    def __init__(
        self,
        config: LlamaConfig | str,
        params=None,
        *,
        max_slots: int,
        num_pages: int,
        page_size: int,
        mesh=None,
        seed: int = 0,
        attention_impl: str = "auto",
        lora_config=None,
    ):
        self.config = PRESETS[config] if isinstance(config, str) else config
        if params is None:
            params = init_params(self.config, jax.random.PRNGKey(seed))
        self.mesh = mesh
        self.max_slots = max_slots
        self.page_size = page_size
        # "paged" = v2 staging-buffer Pallas kernel (pool read-only per
        # K-step dispatch, token carry folded into the online softmax,
        # one batched commit scatter per dispatch — HBM per step
        # proportional to per-SLOT live context); "dense" = bucketed
        # gather (cost tracks the batch-MAX live context); "auto" =
        # paged on TPU backends, dense elsewhere (resolve_attention_impl).
        self.attention_impl = resolve_attention_impl(attention_impl, mesh)
        self.paged_attention = self.attention_impl == "paged"
        # shard_map the kernel over tp when the pool is head-sharded;
        # single-axis (dp-only) meshes keep the plain call. pp meshes
        # (pure OR composed with tp) never use it: the pp decode loop is
        # itself the manual region — flattened over {"pp","tp"} when tp
        # composes (round 15) — and calls the kernel on local arrays.
        self._attn_mesh = (
            mesh if self.paged_attention and mesh is not None
            and mesh.shape.get("pp", 1) == 1
            and mesh.shape.get("tp", 1) > 1 else None)
        pages = init_pages(self.config, num_pages, page_size)
        self._replicated = None
        self._pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if self._pp > 1:
            # Pipeline-parallel: layers (params AND page pool) shard over
            # the pp axis; shard_map programs in pp_model.py rotate
            # activations stage->stage (ref vllm_models.py:117-168 PP).
            # tp COMPOSES inside the stages: dense programs stay manual
            # over pp only (tp auto — XLA partitions from the params'
            # shardings), while the PAGED decode loop flattens to one
            # manual region over {"pp","tp"} (round 15) because the
            # Pallas kernel cannot sit under an auto-tp partition — the
            # reference runs TP x PP engines via vLLM (vllm_models.py:117).
            from jax.sharding import NamedSharding, PartitionSpec

            from ..models.llama import param_axes
            from ..parallel.sharding import logical_sharding, shard_params

            tp = mesh.shape.get("tp", 1)
            if tp > 1 and self.config.n_kv_heads % tp:
                raise ValueError(
                    f"n_kv_heads={self.config.n_kv_heads} not divisible by tp={tp}")
            if self.config.n_layers % self._pp:
                raise ValueError(
                    f"n_layers={self.config.n_layers} not divisible by pp={self._pp}")
            if max_slots % self._pp:
                raise ValueError(
                    f"max_slots={max_slots} not divisible by pp={self._pp} "
                    "(decode pipelines over slot groups)")
            rep = NamedSharding(mesh, PartitionSpec())
            # param_axes maps "layers"->pp and heads/mlp/vocab->tp, so the
            # stacked layer arrays come out sharded over BOTH axes.
            params = shard_params(params, param_axes(self.config), mesh)
            self._pages_sharding = logical_sharding(
                mesh, ("layers", None, "kv_heads", None, "head_dim"))
            pages = jax.device_put(
                pages, {"k": self._pages_sharding, "v": self._pages_sharding})
            self._replicated = rep
        elif mesh is not None:
            # Tensor-parallel: params shard by the model's logical axes
            # (heads/kv_heads/mlp -> tp), the page pool by kv_heads; the
            # same jitted programs then run SPMD with XLA collectives
            # (the multi-chip path the reference gets from vLLM TP).
            from jax.sharding import NamedSharding, PartitionSpec

            from ..models.llama import param_axes
            from ..parallel.sharding import logical_sharding, shard_params

            tp = mesh.shape.get("tp", 1)
            if self.config.n_kv_heads % tp:
                raise ValueError(
                    f"n_kv_heads={self.config.n_kv_heads} not divisible by tp={tp}")
            params = shard_params(params, param_axes(self.config), mesh)
            self._pages_sharding = logical_sharding(
                mesh, ("layers", None, "kv_heads", None, "head_dim"))
            pages = jax.device_put(
                pages, {"k": self._pages_sharding, "v": self._pages_sharding})
            self._replicated = NamedSharding(mesh, PartitionSpec())
        self.lora_config = lora_config
        self.lora_stack = None
        if lora_config is not None:
            if mesh is not None and mesh.shape.get("tp", 1) > 1:
                raise ValueError("lora serving does not shard stacks over "
                                 "tp (adapters are head-stacked; use pp or "
                                 "a single device)")
            from .lora import init_lora_stack

            self.lora_stack = init_lora_stack(
                self.config, lora_config.max_loras, lora_config.max_rank)
            if self._pp > 1:
                # Stacks shard over pp on their LAYER axis, exactly like
                # params["layers"], so pp_model's local layer indices
                # address the local stack shard directly (round 8:
                # LoRA threads through the pp pipeline).
                from jax.sharding import NamedSharding, PartitionSpec

                lora_sharding = NamedSharding(mesh, PartitionSpec("pp"))
                self.lora_stack = {
                    k: jax.device_put(v, lora_sharding)
                    for k, v in self.lora_stack.items()}
            elif mesh is not None:
                self.lora_stack = jax.device_put(
                    self.lora_stack, self._replicated)
        self.params = params
        self.pages = pages
        self._key = jax.random.PRNGKey(seed ^ 0x5EED)
        # handle -> device hidden state [E] awaiting first-token sampling
        self._hidden: dict[int, Any] = {}
        # Serializes every read/replace of self.pages: migration imports
        # and exports run on REQUEST threads while the engine loop keeps
        # dispatching (donating the pool buffer each step) — without the
        # lock an exporter could np.asarray a just-donated (deleted)
        # buffer, or an import could race a decode's donation.
        self._pages_lock = threading.RLock()

        if self._pp > 1:
            # pp programs define their shardings via shard_map out_specs
            # (pages staged over pp, tokens/hidden/key replicated).
            from .pp_model import pp_decode_loop, pp_prefill_chunk, pp_prefill_chunks

            self._key = jax.device_put(self._key, self._replicated)
            self._prefill = functools.partial(pp_prefill_chunk, mesh=mesh)
            self._prefill_many = functools.partial(pp_prefill_chunks, mesh=mesh)
            self._decode_loop = functools.partial(pp_decode_loop, mesh=mesh)
            self._sample_first = jax.jit(
                sample_first_batch.__wrapped__,
                out_shardings=(self._replicated, self._replicated))
            # pp prefill scatters rows at (page, offset) granularity
            # since round 15, so partial-block COW sharing works here
            # too: the fork copy is a page-axis gather/scatter XLA
            # partitions per layer shard without any manual region.
            pg = {"k": self._pages_sharding, "v": self._pages_sharding}
            self._copy_pages = jax.jit(
                copy_pages.__wrapped__, donate_argnames=("pages",),
                out_shardings=pg)
            # pp pools shard layers across the pipeline's manual region;
            # the host-array export/import path below assumes the whole
            # [L, P, ...] pool is addressable — KV migration stays off
            # (the one residue of this round, noted in ROADMAP).
            self._write_pages = None
            # Speculative verify doesn't thread the pp tick loop yet
            # (the staged-per-stage carry would need a per-stage verify
            # program) — pp engines decode plain.
            self._verify = None
        elif self._replicated is not None:
            # Re-jit the model programs with EXPLICIT output shardings:
            # token/key/hidden outputs pinned replicated — on a
            # multi-process mesh an output with an arbitrary XLA-chosen
            # sharding cannot be np.asarray'd (or indexed) by every
            # process; replicated outputs can. Pages keep their kv_heads
            # sharding and stay donated.
            rep = self._replicated
            pg = {"k": self._pages_sharding, "v": self._pages_sharding}
            self._decode_loop = jax.jit(
                decode_loop.__wrapped__,
                static_argnames=("config", "page_size", "n_steps", "paged",
                                 "live_pages", "attn_mesh"),
                donate_argnames=("pages",),
                out_shardings=(rep, rep, pg),
            )
            self._sample_first = jax.jit(
                sample_first_batch.__wrapped__, out_shardings=(rep, rep))
            self._prefill = jax.jit(
                prefill_chunk.__wrapped__,
                static_argnames=("config", "page_size", "live_pages"),
                donate_argnames=("pages",),
                out_shardings=(pg, rep),
            )
            self._mixed = jax.jit(
                mixed_dispatch.__wrapped__,
                static_argnames=("config", "page_size", "n_steps", "paged",
                                 "live_pages", "prefill_live_pages",
                                 "attn_mesh"),
                donate_argnames=("pages",),
                out_shardings=(rep, rep, pg, rep),
            )
            self._copy_pages = jax.jit(
                copy_pages.__wrapped__, donate_argnames=("pages",),
                out_shardings=pg)
            self._write_pages = jax.jit(
                write_pages.__wrapped__, donate_argnames=("pages",),
                out_shardings=pg)
            self._verify = jax.jit(
                verify_block.__wrapped__,
                static_argnames=("config", "page_size", "n_draft", "paged",
                                 "live_pages", "attn_mesh"),
                donate_argnames=("pages",),
                out_shardings=(rep, rep, rep, pg),
            )
        else:
            self._decode_loop = decode_loop
            self._sample_first = sample_first_batch
            self._prefill = prefill_chunk
            self._mixed = mixed_dispatch
            self._copy_pages = copy_pages
            self._write_pages = write_pages
            self._verify = verify_block

    def _put(self, x: np.ndarray):
        """Host input -> device, replicated over the mesh when present (a
        multi-process jit requires global inputs, not bare numpy)."""
        if self._replicated is not None:
            return jax.device_put(x, self._replicated)
        return jnp.asarray(x)

    @staticmethod
    def _bucket_pages(needed: int, max_pages: int) -> int:
        """Round a live-page requirement up to a power of two (≥ 8), so
        the static ``live_pages`` cap takes O(log(max_pages)) distinct
        values — bounding recompiles while keeping attention cost
        proportional to live context rather than pool capacity."""
        b = 8
        while b < needed:
            b *= 2
        return min(b, max_pages)

    @property
    def supports_weight_residency(self) -> bool:
        """Host-tier weight demotion/promotion (``llm/weights.py``):
        single-device executors only — mesh-sharded params own their
        placement and a plain ``device_put`` would lose it."""
        return self._replicated is None

    def install_adapter(self, slot: int, arrays: dict) -> None:
        """Write one adapter's padded A/B arrays into stack slot ``slot``
        (the ``LoRAManager``'s device hook). Arrays ride ``_put`` so a
        mesh-sharded stack (pp) takes them as replicated global inputs."""
        from .lora import _install

        self.lora_stack = _install(
            self.lora_stack, self._put(np.int32(slot)),
            {k: self._put(np.asarray(v)) for k, v in arrays.items()})

    # ------------------------------------------------------------- operations
    def prefill(self, block_table: np.ndarray, tokens: np.ndarray,
                start_pos: int, handle: int | None, take: int,
                lora_slot: int = 0) -> None:
        if self._pp > 1:
            kwargs = {}
            if self.lora_stack is not None:
                kwargs["lora"] = self.lora_stack
                kwargs["lora_slot"] = self._put(np.int32(lora_slot))
        else:
            # Context gathered is [0, start_pos): cap the gather width.
            kwargs = {"live_pages": self._bucket_pages(
                -(-int(start_pos) // self.page_size), block_table.shape[0])}
            if self.lora_stack is not None:
                kwargs["lora"] = self.lora_stack
                kwargs["lora_slot"] = self._put(np.int32(lora_slot))
        with self._pages_lock:
            self.pages, hidden = self._prefill(
                self.params, self.pages,
                self._put(block_table.astype(np.int32)),
                self._put(tokens.astype(np.int32)),
                self._put(np.int32(start_pos)),
                config=self.config, page_size=self.page_size, **kwargs,
            )
        if handle is not None:  # final chunk: stash for first-token sampling
            self._hidden[handle] = hidden[take - 1]

    @property
    def pipelined_prefill_depth(self) -> int:
        """Max consecutive chunks one prefill dispatch pipelines (1 = no
        pipelining). Longer wavefronts amortize the (pp-1)-tick warmup:
        stage utilization is m/(m+pp-1), so 8 chunks through 2 stages
        runs at 89% vs 67% for 2."""
        return max(self._pp, 8) if self._pp > 1 else 1

    def prefill_many(self, block_table: np.ndarray, tokens_m: np.ndarray,
                     start_pos: int, handle: int | None, take: int) -> None:
        """``m`` consecutive same-size chunks of ONE sequence in a single
        chunk-pipelined dispatch (``pp_model.pp_prefill_chunks``); when
        ``handle`` is set, the LAST chunk's position ``take - 1`` hidden
        is stashed for first-token sampling."""
        with self._pages_lock:
            self.pages, hiddens = self._prefill_many(
                self.params, self.pages,
                self._put(block_table.astype(np.int32)),
                self._put(tokens_m.astype(np.int32)),
                self._put(np.int32(start_pos)),
                config=self.config, page_size=self.page_size,
            )
        if handle is not None:
            self._hidden[handle] = hiddens[-1][take - 1]

    def drop_handle(self, handle: int) -> None:
        self._hidden.pop(handle, None)

    def sample_first(self, handles: list[int], temps: np.ndarray) -> np.ndarray:
        """One dispatch + one sync for every pending first token. Pads to
        ``max_slots`` so the program compiles once, not per batch size."""
        m = len(handles)
        stack = [self._hidden.pop(h) for h in handles]
        hiddens = jnp.stack(stack + [stack[0]] * (self.max_slots - m))
        padded = np.zeros(self.max_slots, np.float32)
        padded[:m] = temps[:m]
        toks, self._key = self._sample_first(
            hiddens, self.params["lm_head"], self._put(padded), self._key)
        return np.asarray(toks)[:m]

    def _decode_kwargs(self, pos: np.ndarray, n_steps: int,
                       block_tables: np.ndarray, lora_idx) -> dict:
        """Static decode kwargs shared by ``decode`` and ``mixed``."""
        if self.paged_attention:
            # The kernel only reads POOL context [0, pos): tokens
            # generated mid-dispatch ride the staging carry, so the
            # page bound ignores n_steps entirely — a strictly
            # tighter grid than the dense bound below.
            needed = max(1, (int(pos.max()) + self.page_size - 1)
                         // self.page_size)
        else:
            # Dense attends in-pool: positions reach
            # max(pos) + n_steps - 1 by the last fused step.
            needed = (int(pos.max()) + n_steps - 1) // self.page_size + 1
        kwargs = {
            "paged": self.paged_attention,
            "live_pages": self._bucket_pages(needed, block_tables.shape[1]),
            "attn_mesh": self._attn_mesh,
        }
        if self.lora_stack is not None:
            kwargs["lora"] = self.lora_stack
            kwargs["lora_idx"] = self._put(
                (lora_idx if lora_idx is not None
                 else np.zeros(block_tables.shape[0], np.int32)).astype(np.int32))
        return kwargs

    def decode(self, block_tables: np.ndarray, tokens: np.ndarray,
               pos: np.ndarray, temps: np.ndarray, eos_ids: np.ndarray,
               remaining: np.ndarray, n_steps: int,
               lora_idx: np.ndarray | None = None) -> np.ndarray:
        if self._pp > 1:
            kwargs = {}
            if self.paged_attention:
                # Same pool-context-only bound as the unpipelined paged
                # path: staged tokens ride the per-stage carry, so the
                # kernel grid ignores n_steps entirely.
                needed = max(1, (int(pos.max()) + self.page_size - 1)
                             // self.page_size)
                kwargs["paged"] = True
                kwargs["live_pages"] = self._bucket_pages(
                    needed, block_tables.shape[1])
            if self.lora_stack is not None:
                kwargs["lora"] = self.lora_stack
                kwargs["lora_idx"] = self._put(
                    (lora_idx if lora_idx is not None
                     else np.zeros(block_tables.shape[0], np.int32)
                     ).astype(np.int32))
        else:
            kwargs = self._decode_kwargs(pos, n_steps, block_tables, lora_idx)
        with self._pages_lock:
            toks, self._key, self.pages = self._decode_loop(
                self.params, self.pages,
                self._put(block_tables.astype(np.int32)),
                self._put(tokens.astype(np.int32)),
                self._put(pos.astype(np.int32)),
                self._put(temps.astype(np.float32)),
                self._put(eos_ids.astype(np.int32)),
                self._put(remaining.astype(np.int32)),
                self._key, config=self.config, page_size=self.page_size,
                n_steps=n_steps, **kwargs,
            )
        return np.asarray(toks)  # [n_steps, slots] — the one sync

    @property
    def supports_speculation(self) -> bool:
        """Speculative verify dispatch (``model.verify_block``): off the
        pp path (the per-stage tick loop doesn't thread the verify
        program yet) and without a LoRA stack (the chunk forward doesn't
        carry per-slot adapter deltas — those slots decode plain)."""
        return self._verify is not None and self.lora_stack is None

    def verify(self, block_tables: np.ndarray, tokens_mat: np.ndarray,
               pos: np.ndarray, temps: np.ndarray, eos_ids: np.ndarray,
               remaining: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Score one drafted continuation per slot in ONE dispatch.

        tokens_mat: [slots, K+1] int32 — column 0 the current token,
        columns 1..K the draft (-1 pads). Returns ``(tokens [K+1,
        slots], live [K+1, slots])`` — the emitted-token matrix and its
        per-step liveness mask (see ``model.verify_block``)."""
        assert self.supports_speculation
        n_draft = int(tokens_mat.shape[1]) - 1
        # The verify forward reads POOL context [0, pos) only — chunk
        # tokens ride the staging carry — so the page bound ignores the
        # draft depth, like the paged decode bound.
        needed = max(1, (int(pos.max()) + self.page_size - 1)
                     // self.page_size)
        with self._pages_lock:
            toks, live, self._key, self.pages = self._verify(
                self.params, self.pages,
                self._put(block_tables.astype(np.int32)),
                self._put(tokens_mat.astype(np.int32)),
                self._put(pos.astype(np.int32)),
                self._put(temps.astype(np.float32)),
                self._put(eos_ids.astype(np.int32)),
                self._put(remaining.astype(np.int32)),
                self._key, config=self.config, page_size=self.page_size,
                n_draft=n_draft, paged=self.paged_attention,
                live_pages=self._bucket_pages(needed, block_tables.shape[1]),
                attn_mesh=self._attn_mesh,
            )
        return np.asarray(toks), np.asarray(live)

    @property
    def supports_prefix_cow(self) -> bool:
        """Copy-on-write prefix sharing: needs ``copy_pages`` plus the
        row-granular prefill scatter (mid-page suffix starts). Both hold
        on every path since round 15 — pp prefill writes rows at
        ``(page, offset)`` granularity now, so a mid-page suffix start
        no longer clobbers a COW fork's copied prefix rows."""
        return self._copy_pages is not None

    def copy_pages(self, src, dst) -> None:
        """Fork shared pages: device-copies pages ``src`` onto ``dst``
        (all layers, one dispatch). Ordered with the prefill/decode
        stream — the engine calls it immediately before the first chunk
        that writes into the fork."""
        with self._pages_lock:
            self.pages = self._copy_pages(
                self.pages, self._put(np.asarray(src, np.int32)),
                self._put(np.asarray(dst, np.int32)))

    # --------------------------------------------------------- KV migration
    @property
    def supports_kv_migration(self) -> bool:
        """Page export/import for KV migration (disaggregated serving,
        spill migration, tiered host-RAM KV). Available off the pp path —
        pp pools shard layers across the pipeline stages, so the
        host-array gather/scatter below cannot address the whole pool."""
        return self._write_pages is not None

    def export_pages(self, page_ids) -> dict:
        """Device→host gather of the named pages' K/V across every
        layer: the wire payload of a KV migration chunk. The caller must
        hold refcounts on the pages (the engine pins them) so the
        allocator cannot recycle them mid-pull.

        Returns ``{"k", "v"}`` host arrays of shape [L, m, KH, page, D].
        """
        ids = np.asarray(page_ids, np.int32)
        with self._pages_lock:
            k = self.pages["k"][:, ids]
            v = self.pages["v"][:, ids]
            return {"k": np.asarray(k), "v": np.asarray(v)}

    def import_pages(self, page_ids, data) -> None:
        """Host→device scatter of migrated page contents into freshly
        reserved pages (one page-granular write on the donated pool —
        never pool-sized). Thread-safe against the engine loop via the
        pages lock; the destination pages are allocator-reserved, so the
        write is disjoint from every live block table by construction."""
        with self._pages_lock:
            self.pages = self._write_pages(
                self.pages, self._put(np.asarray(page_ids, np.int32)),
                self._put(np.asarray(data["k"])),
                self._put(np.asarray(data["v"])))

    @property
    def supports_mixed_dispatch(self) -> bool:
        """Mixed (prefill+decode fused) dispatch: available off the pp
        path (the pp tick loop doesn't thread the fused program yet).
        With a LoRA stack the DECODE half of the fused program carries
        per-slot adapter deltas (``_decode_kwargs`` threads lora/
        lora_idx), so mixed-adapter decode batches still run in ONE
        dispatch; only adapter-bound PREFILL stays on the legacy chunk
        path (the fused prefill ops don't carry per-op slot plumbing —
        the engine's plan selector excludes those prompts)."""
        return self._pp == 1

    def mixed(self, prefill_plans: list, block_tables: np.ndarray,
              tokens: np.ndarray, pos: np.ndarray, temps: np.ndarray,
              eos_ids: np.ndarray, remaining: np.ndarray, n_steps: int,
              lora_idx: np.ndarray | None = None) -> np.ndarray:
        """ONE dispatch carrying the full decode burst plus up to the
        engine's prefill token budget of prompt chunks.

        prefill_plans: list of dicts ``{"block_table", "tokens",
        "start_pos", "handle", "take"}`` — page-aligned chunks of DISTINCT
        admitted prompts; a plan with a ``handle`` is its prompt's final
        chunk and stashes position ``take - 1``'s hidden state for
        first-token sampling, exactly like ``prefill``.
        """
        assert self.supports_mixed_dispatch
        ops = []
        op_live = []
        for p in prefill_plans:
            bt = np.asarray(p["block_table"], np.int32)
            ops.append((self._put(bt),
                        self._put(np.asarray(p["tokens"], np.int32)),
                        self._put(np.int32(p["start_pos"]))))
            op_live.append(self._bucket_pages(
                -(-int(p["start_pos"]) // self.page_size), bt.shape[0]))
        kwargs = self._decode_kwargs(pos, n_steps, block_tables, lora_idx)
        with self._pages_lock:
            toks, self._key, self.pages, hiddens = self._mixed(
                self.params, self.pages, tuple(ops),
                self._put(block_tables.astype(np.int32)),
                self._put(tokens.astype(np.int32)),
                self._put(pos.astype(np.int32)),
                self._put(temps.astype(np.float32)),
                self._put(eos_ids.astype(np.int32)),
                self._put(remaining.astype(np.int32)),
                self._key, config=self.config, page_size=self.page_size,
                n_steps=n_steps, prefill_live_pages=tuple(op_live), **kwargs,
            )
        for p, hidden in zip(prefill_plans, hiddens):
            if p.get("handle") is not None:
                self._hidden[p["handle"]] = hidden[p["take"] - 1]
        return np.asarray(toks)  # [n_steps, slots] — still the one sync

    @property
    def lm_head(self):
        return self.params["lm_head"]
